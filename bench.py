"""Benchmark harness — one JSON line for the driver.

Metric: training throughput (samples/sec) of the reference parity workload —
MLModel (LeNet) on CIFAR-10-shaped data at global batch 32, full train step
(forward, loss, backward, SGD update + on-device metric), driven through the
framework's Trainer machinery (prefetched Loader + compiled step), i.e. the
exact configuration behind the reference's only recorded number:
822–966 samples/s on local CPU (01 nb cell-12; BASELINE.md).  ``vs_baseline``
divides by the best reference figure (966).

Run ``python bench.py --extended`` for the north-star model table
(ResNet-50, ViT-B/16, BERT-base, GPT-2-124M step throughput) printed as
extra human-readable lines before the JSON line.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from ml_trainer_tpu.trainer import enable_compilation_cache
from ml_trainer_tpu.utils.profiler import StepTimer

enable_compilation_cache()

BASELINE_SAMPLES_PER_SEC = 966.0  # reference train throughput, BASELINE.md

# Host-wide tunnel mutex (ml_trainer_tpu/utils/tunnel.py): every tunnel
# client on this host — this bench, scripts/bench_decode.py, the
# watcher's probes, the recovery script's stages — serializes on one
# flock, because concurrent dials are the leading suspect for the
# tunnel's recurring wedge (r3/r4: hand sessions succeeded while the
# driver's bench, racing the background watcher's probes, got nothing
# but init hangs).
from ml_trainer_tpu.utils.tunnel import (  # noqa: E402
    acquire_tunnel_lock as _acquire_tunnel_lock,
    utcnow as _utcnow,
)


def _probe_backend_subprocess(timeout: float) -> str:
    """Try initializing the default backend in a THROWAWAY subprocess.

    The TPU tunnel here can hang at init (not just error) — r01's records
    show both modes.  A hang inside this process would wedge it past any
    retry logic, so the probe runs where it can be killed.  Returns "" on
    success or a failure description.
    """
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(len(jax.devices()), jax.default_backend())"],
            timeout=timeout, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        return f"backend init hang (> {timeout:.0f}s)"
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        return f"backend init error: {tail[-1] if tail else 'rc=' + str(r.returncode)}"
    print(f"# backend probe OK: {r.stdout.strip()}", file=sys.stderr)
    return ""


def _init_devices_with_retry(probe_timeout=None, window_secs=None):
    """Initialize the JAX backend, surviving TPU UNAVAILABLE errors AND
    init hangs.  Probes in a subprocess (killable) and KEEPS probing with
    backoff until ``window_secs`` is spent — round-3's driver run showed
    a wedged tunnel outlasting a fixed 3-attempt budget while recovering
    minutes later.

    The default window is a deliberate risk trade, not headroom
    maximization: the driver's own kill timeout is UNKNOWN, and a run it
    kills leaves NO record at all — strictly worse than a CPU-fallback
    record.  Round 3 proved the driver tolerates ~12.5 min of probing
    plus the bench itself (that fallback record landed), so the default
    stays at 660s probing + ~2 min bench ≈ the proven total; a 900s
    window would push ~18 min total into unproven territory where the
    likeliest failure is losing the record entirely.  Hand-run sessions
    (no driver timeout) should raise ``BENCH_PROBE_WINDOW_SECS`` for
    maximum recovery odds.  The
    per-probe budget stays at 240s (env ``BENCH_PROBE_TIMEOUT_SECS``):
    a slow-but-healthy init that needs 150-240s must be able to SUCCEED
    within one probe — a shorter per-probe cap would doom every attempt
    no matter how long the window.  Falls back to CPU only after the
    window, so the driver always gets a parseable JSON line.  Returns
    (devices, note, probe_log) — probe_log is the per-attempt diagnostic
    trail (timestamp, duration, error class, lock contention) that goes
    into the emitted record verbatim, so a failed driver run documents
    its own failure mode instead of just "TPU unavailable"."""
    import os

    if probe_timeout is None:
        probe_timeout = float(
            os.environ.get("BENCH_PROBE_TIMEOUT_SECS", "240")
        )
    if window_secs is None:
        window_secs = float(os.environ.get("BENCH_PROBE_WINDOW_SECS", "660"))
    deadline = time.time() + window_secs
    probe_log: list = []
    if not _acquire_tunnel_lock(deadline, probe_log):
        jax.config.update("jax_platforms", "cpu")
        return (
            jax.devices(),
            "TPU not dialed (tunnel lock held by another client for the "
            "whole probe window); measured on CPU fallback",
            probe_log,
        )
    attempt, last = 0, ""
    while True:
        attempt += 1
        t0 = time.time()
        last = _probe_backend_subprocess(probe_timeout)
        probe_log.append(
            {"t": _utcnow(), "attempt": attempt,
             "secs": round(time.time() - t0, 1), "result": last or "ok"}
        )
        if not last:
            return jax.devices(), "", probe_log
        print(
            f"# backend probe attempt {attempt} failed: {last} "
            f"({max(0.0, deadline - time.time()):.0f}s of window left)",
            file=sys.stderr,
        )
        if time.time() >= deadline:
            break
        time.sleep(min(10.0 * attempt, 60.0))
    # Fall back to CPU in-process: safe because this process has not touched
    # the default backend yet.
    jax.config.update("jax_platforms", "cpu")
    return (
        jax.devices(),
        f"TPU unavailable ({last}); measured on CPU fallback",
        probe_log,
    )


def _steady_state_rate(step, state, batches, warmup=5, iters=50):
    """Steps/sec via the fenced StepTimer (compile/warmup excluded)."""
    timer = StepTimer(warmup=warmup)
    for i in range(warmup + iters):
        state, *_ = step(state, *batches[i % len(batches)])
        timer.tick(state, 1)
    return timer.rate(), state


PARITY_DS_SIZE = 8192  # synthetic dataset behind bench_parity

# Default K: the parity workload is dispatch-bound (a 62K-param LeNet step
# executes in microseconds; every dispatch pays a host->device round trip
# — over the remote tunnel, milliseconds), so throughput scales with K
# until the chained execution dwarfs the round trip.  K=32 measured
# 18.8ms/dispatch on the 07-30 tunnel session (~14ms of it round trip);
# K=128 amortizes the same trip over 4x the samples.  Trajectory is
# identical to per-batch stepping regardless of K (tests/test_trainer.py).
PARITY_K = 128


def _effective_k(batch_size: int, steps_per_execution: int = PARITY_K) -> int:
    """The multi-step K bench_parity will actually use — large batches
    leave too few batches per epoch and clamp K down to 1."""
    return max(
        1, min(steps_per_execution, PARITY_DS_SIZE // batch_size // 2)
    )


def bench_parity(batch_size=32, steps_per_execution=PARITY_K):
    """The reference workload through the real Trainer train step.

    Uses the Trainer's multi-step fast path (``steps_per_execution`` K
    optimizer steps per dispatch via lax.scan — trajectory identical to
    per-batch stepping, verified in tests/test_trainer.py) so the number
    reflects the chip, not Python dispatch."""
    from ml_trainer_tpu import Trainer, MLModel
    from ml_trainer_tpu.data import SyntheticCIFAR10
    from ml_trainer_tpu.utils.functions import custom_pre_process_function

    ds = SyntheticCIFAR10(
        size=PARITY_DS_SIZE, transform=custom_pre_process_function()
    )
    # Large batch sizes leave few batches per epoch: cap K so at least one
    # full stack exists, falling back to the per-batch path at K=1.
    k = _effective_k(batch_size, steps_per_execution)
    trainer = Trainer(
        MLModel(), datasets=(ds, ds), epochs=1, batch_size=batch_size,
        model_dir="/tmp/bench_model", metric="accuracy", lr=0.01,
        steps_per_execution=k,
    )
    # Pre-materialize transformed, stacked device batches so we measure the
    # compiled program (the input pipeline overlaps via prefetch during real
    # training).
    from ml_trainer_tpu.data import prefetch_to_device

    if k == 1:
        batches = [
            (x, y, jnp.asarray(1.0, jnp.float32))
            for _, (x, y) in zip(
                range(16),
                prefetch_to_device(
                    trainer.train_loader, size=2,
                    sharding=trainer._batch_sharding,
                ),
            )
        ]
        rate, _ = _steady_state_rate(trainer._train_step, trainer.state, batches)
        return rate * batch_size
    raw = [b for _, b in zip(range(2 * k), trainer.train_loader)]
    stacked = [
        tuple(np.stack(t) for t in zip(*raw[i * k:(i + 1) * k]))
        for i in range(len(raw) // k)
    ]
    batches = [
        (xs, ys, jnp.asarray(1.0, jnp.float32))
        for xs, ys in prefetch_to_device(
            iter(stacked), size=2, sharding=trainer._stacked_sharding
        )
    ]
    rate, _ = _steady_state_rate(
        trainer._train_multi_step, trainer.state, batches, warmup=2, iters=8
    )
    return rate * batch_size * k


def bench_loaders(size=4096, batch_size=256, epochs=4):
    """Host input-pipeline throughput: Python Loader vs native C++ worker,
    same fused augmentation (crop/flip/normalize)."""
    from ml_trainer_tpu.data import Loader, SyntheticCIFAR10
    from ml_trainer_tpu.data.native import NativeLoader, native_available
    from ml_trainer_tpu.utils.functions import custom_pre_process_function

    ds = SyntheticCIFAR10(size=size, transform=custom_pre_process_function())

    def rate(loader):
        list(loader)  # warm (build lib / allocate)
        t0 = time.perf_counter()
        n = 0
        for _ in range(epochs):
            for x, _y in loader:
                n += x.shape[0]
        return n / (time.perf_counter() - t0)

    py = rate(Loader(ds, batch_size=batch_size, shuffle=True, seed=0))
    print(f"# input pipeline python: {py:,.0f} samples/s")
    if native_available():
        nat = rate(NativeLoader(ds, batch_size=batch_size, seed=0))
        print(
            f"# input pipeline native (C++): {nat:,.0f} samples/s "
            f"({nat / py:.2f}x python)"
        )
    else:
        # Recovery's done-check keys on the 'input pipeline native' line;
        # emit it in the unavailable case too so a host that cannot build
        # the C++ worker still completes the stage.
        print("# input pipeline native (C++): unavailable on this host")


def bench_serve(n_requests=32, mean_interarrival=0.01, max_batch=8,
                seed=0):
    """Serving leg: the continuous-batching engine vs a dynamic-batching
    ``generate_ragged`` baseline on the SAME ragged Poisson arrival trace.

    The workload is serving-shaped: ragged prompt lengths, ragged
    per-request token budgets (most requests short, a heavy tail long —
    the distribution that makes one-shot batching convoy), Poisson
    arrivals (real sleeps on a compressed timescale).  The baseline is
    the strongest server one can write on the one-shot API: harvest
    everything queued at each completion boundary and run it through
    ``generate_ragged`` (length buckets, pow2 batch padding) decoded to
    the harvested batch's LARGEST budget — short requests ride out the
    longest one (the convoy), and late arrivals wait for the whole
    batch.  The engine admits each request into a slot at the next token
    boundary and frees the slot the moment its budget is spent.  Both
    paths are warmed over the workload's compile shapes first, count
    only USEFUL tokens (each request's own budget), and are timed from
    first submission to last completion.
    Returns {"engine_tokens_per_sec", "baseline_tokens_per_sec", ...}.
    """
    import queue as _queue
    import threading

    from ml_trainer_tpu.generate import generate, generate_ragged
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.serving import Server

    model = get_model("gpt2_tiny", max_len=128)
    variables = jax.jit(model.init, static_argnames="train")(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32),
        train=False,
    )
    rng = np.random.default_rng(seed)
    # Few distinct lengths/budgets (still ragged): keeps the baseline's
    # (length, batch, max_new) compile space warmable so the measured
    # gap is scheduling, not XLA compilation.
    lengths = rng.choice([5, 9], size=n_requests)
    budgets = rng.choice([4, 64], size=n_requests, p=[0.75, 0.25])
    prompts = [
        rng.integers(0, model.vocab_size, size=l).astype(np.int32)
        for l in lengths
    ]
    arrivals = np.cumsum(rng.exponential(mean_interarrival, n_requests))
    total_tokens = int(budgets.sum())  # useful tokens, both paths

    def run_engine():
        with Server(model, variables, max_batch=max_batch,
                    max_queue=n_requests) as srv:
            # Warm the engine's compiled programs (prefill buckets +
            # decode step) outside the timed window.
            for l in sorted(set(int(x) for x in lengths)):
                srv.complete(prompts[list(lengths).index(l)], 2,
                             timeout=300)
            t0 = time.perf_counter()
            streams = []
            for i, p in enumerate(prompts):
                wait = arrivals[i] - (time.perf_counter() - t0)
                if wait > 0:
                    time.sleep(wait)
                streams.append(srv.submit(p, int(budgets[i])))
            lat = []
            for i, s in enumerate(streams):
                s.result(timeout=600)
                lat.append(
                    s.request.finished_at - s.request.submitted_at
                )
            elapsed = time.perf_counter() - t0
        return total_tokens / elapsed, float(np.median(lat))

    def run_baseline():
        # Warm every (length, pow2-batch<=max_batch, batch-max-budget)
        # program the harvest loop can hit.
        for l in sorted(set(int(x) for x in lengths)):
            p = prompts[list(lengths).index(l)]
            for m in sorted(set(int(x) for x in budgets)):
                b = 1
                while b <= max_batch:
                    generate(model, variables, np.stack([p] * b), m)
                    b *= 2
        pending: _queue.Queue = _queue.Queue()

        def feeder():
            t0 = time.perf_counter()
            for i, p in enumerate(prompts):
                wait = arrivals[i] - (time.perf_counter() - t0)
                if wait > 0:
                    time.sleep(wait)
                pending.put((i, p, time.perf_counter()))

        th = threading.Thread(target=feeder)
        t0 = time.perf_counter()
        th.start()
        done, lat = 0, []
        while done < n_requests:
            batch = [pending.get()]
            while len(batch) < max_batch:
                try:
                    batch.append(pending.get_nowait())
                except _queue.Empty:
                    break
            # One-shot API: the whole batch decodes to its largest
            # budget (per-request early exit is exactly what the API
            # cannot do); surplus tokens are discarded, not counted.
            horizon = max(int(budgets[i]) for i, _, _ in batch)
            generate_ragged(
                model, variables, [p for _, p, _ in batch], horizon
            )
            now = time.perf_counter()
            lat.extend(now - t_in for _, _, t_in in batch)
            done += len(batch)
        elapsed = time.perf_counter() - t0
        th.join()
        return total_tokens / elapsed, float(np.median(lat))

    base_tps, base_lat = run_baseline()
    print(f"# serve baseline (generate_ragged): {base_tps:,.1f} tokens/s, "
          f"p50 latency {base_lat * 1e3:,.0f} ms", flush=True)
    eng_tps, eng_lat = run_engine()
    print(f"# serve engine (continuous batching): {eng_tps:,.1f} tokens/s, "
          f"p50 latency {eng_lat * 1e3:,.0f} ms "
          f"({eng_tps / base_tps:.2f}x baseline)", flush=True)
    return {
        "engine_tokens_per_sec": round(eng_tps, 1),
        "baseline_tokens_per_sec": round(base_tps, 1),
        "engine_p50_latency_ms": round(eng_lat * 1e3, 1),
        "baseline_p50_latency_ms": round(base_lat * 1e3, 1),
        "speedup": round(eng_tps / base_tps, 2),
        "n_requests": n_requests,
        "useful_tokens": total_tokens,
        "backend": jax.default_backend(),
    }


def bench_serve_replay(n_requests=48, n_tenants=3, shared_frac=0.8,
                       mean_interarrival=0.002, max_batch=8, seed=0,
                       page_size=16, shared_len=160, out_path=None,
                       spec_check=True):
    """Multi-tenant ragged replay: PAGED engine (page pool + radix
    prefix cache + tenant scheduler) vs the CONTIGUOUS engine on the
    same trace.

    The trace is production-shaped serving traffic: ``n_tenants``
    tenants with Poisson arrivals, ``shared_frac`` of each tenant's
    requests opening with that tenant's long shared prefix (system
    prompt / few-shot preamble — ``shared_len`` tokens) followed by a
    short unique suffix, the rest fully unique; ragged budgets with a
    heavy tail.  Both engines replay the identical submissions
    (prompt, budget, tenant, arrival time).

    Method: each engine runs the trace TWICE and the second pass is
    timed — pass 1 warms every compiled shape AND fills the prefix
    cache to steady state, and the compiled-program count is asserted
    constant across the timed pass (the zero-recompile pin).  Greedy
    outputs are asserted byte-identical between the two engines, and
    (``spec_check``) a spec_k mini-replay is asserted identical too.
    Reports sustained tokens/s (useful generated tokens over makespan)
    and TTFT p50/p99.  ``out_path`` writes the JSON artifact
    (docs/serving_replay_cpu.json is the committed copy gated by
    scripts/bench_gate.py).
    """
    from ml_trainer_tpu.generate import _COMPILED, generate
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.serving import Server, TenantConfig

    model = get_model("gpt2_tiny", max_len=256)
    variables = jax.jit(model.init, static_argnames="train")(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32),
        train=False,
    )
    rng = np.random.default_rng(seed)
    tenants = {
        f"tenant{t}": TenantConfig(weight=float(t + 1))
        for t in range(n_tenants)
    }
    prefixes = [
        rng.integers(0, model.vocab_size, shared_len).astype(np.int32)
        for _ in range(n_tenants)
    ]
    trace = []
    for i in range(n_requests):
        t = int(rng.integers(0, n_tenants))
        if rng.random() < shared_frac:
            suffix = rng.integers(
                0, model.vocab_size, int(rng.integers(4, 17))
            ).astype(np.int32)
            prompt = np.concatenate([prefixes[t], suffix])
        else:
            prompt = rng.integers(
                0, model.vocab_size, int(rng.integers(16, 33))
            ).astype(np.int32)
        budget = int(rng.choice([4, 16], p=[0.75, 0.25]))
        trace.append((prompt, budget, f"tenant{t}"))
    arrivals = np.cumsum(rng.exponential(mean_interarrival, n_requests))
    useful_tokens = sum(b for _, b, _ in trace)

    def replay(server, timed: bool):
        t0 = time.perf_counter()
        streams = []
        for i, (prompt, budget, tenant) in enumerate(trace):
            wait = arrivals[i] - (time.perf_counter() - t0)
            if wait > 0:
                time.sleep(wait)
            streams.append(server.submit(prompt, budget, tenant=tenant))
        outs, ttfts = [], []
        for s in streams:
            outs.append(np.asarray(s.result(timeout=600)))
            ttfts.append(s.request.first_token_at - s.request.submitted_at)
        makespan = time.perf_counter() - t0
        ttfts = np.sort(np.asarray(ttfts))
        return {
            "tokens_per_sec": round(useful_tokens / makespan, 1),
            "ttft_p50_ms": round(float(ttfts[len(ttfts) // 2]) * 1e3, 1),
            "ttft_p99_ms": round(
                float(ttfts[min(len(ttfts) - 1,
                                int(0.99 * (len(ttfts) - 1) + 0.5))]) * 1e3,
                1,
            ),
            "makespan_s": round(makespan, 3),
        }, outs

    def run_engine(paged: bool):
        kwargs = dict(max_batch=max_batch, max_queue=n_requests,
                      tenants=dict(tenants))
        if paged:
            kwargs.update(kv_page_size=page_size)
        with Server(model, variables, **kwargs) as srv:
            replay(srv, timed=False)          # warm compiles + prefix cache
            n_warm = len(_COMPILED._data)
            stats, outs = replay(srv, timed=True)
            n_after = len(_COMPILED._data)
            snap = srv.metrics.snapshot()
        stats["compiled_programs_constant"] = n_after == n_warm
        stats["prefix_hit_rate"] = snap["prefix_hit_rate"]
        stats["preemptions"] = snap["preemptions_total"]
        return stats, outs

    contig, contig_outs = run_engine(paged=False)
    print(f"# serve replay contiguous: {contig['tokens_per_sec']:,.1f} "
          f"tokens/s, TTFT p99 {contig['ttft_p99_ms']:,.1f} ms", flush=True)
    paged, paged_outs = run_engine(paged=True)
    print(f"# serve replay paged:      {paged['tokens_per_sec']:,.1f} "
          f"tokens/s, TTFT p99 {paged['ttft_p99_ms']:,.1f} ms "
          f"({paged['tokens_per_sec'] / contig['tokens_per_sec']:.2f}x, "
          f"prefix hit rate {paged['prefix_hit_rate']:.2f})", flush=True)

    identical = all(
        np.array_equal(a, b) for a, b in zip(contig_outs, paged_outs)
    )
    spec_identical = None
    if spec_check:
        # Spec mini-replay: the fixed-K verify window reading through
        # page tables must still be byte-identical to the contiguous
        # spec path (and to generate()).
        mini = trace[: min(6, len(trace))]
        refs = [
            np.asarray(generate(model, variables, p[None], b))[0]
            for p, b, _ in mini
        ]
        spec_outs = {}
        for paged_flag in (False, True):
            kwargs = dict(max_batch=4, max_queue=len(mini), spec_k=4)
            if paged_flag:
                kwargs.update(kv_page_size=page_size)
            with Server(model, variables, **kwargs) as srv:
                ss = [srv.submit(p, b, tenant=t) for p, b, t in mini]
                spec_outs[paged_flag] = [
                    np.asarray(s.result(timeout=600)) for s in ss
                ]
        spec_identical = all(
            np.array_equal(a, b) and np.array_equal(a, r)
            for a, b, r in zip(spec_outs[False], spec_outs[True], refs)
        )
    result = {
        "paged": paged,
        "contiguous": contig,
        "speedup": round(
            paged["tokens_per_sec"] / contig["tokens_per_sec"], 3
        ),
        "ttft_p99_ratio": round(
            paged["ttft_p99_ms"] / contig["ttft_p99_ms"], 3
        ) if contig["ttft_p99_ms"] else None,
        "greedy_byte_identical": identical,
        "spec_byte_identical": spec_identical,
        "n_requests": n_requests,
        "n_tenants": n_tenants,
        "shared_frac": shared_frac,
        "shared_len": shared_len,
        "page_size": page_size,
        "max_batch": max_batch,
        "useful_tokens": useful_tokens,
        "backend": jax.default_backend(),
    }
    if not identical:
        result["error"] = "paged output diverged from contiguous"
    if spec_identical is False:
        result["error"] = "spec paged output diverged"
    if out_path:
        with open(out_path, "w", encoding="utf-8") as fp:
            json.dump(result, fp, indent=1)
        print(f"# serve replay artifact -> {out_path}", flush=True)
    return result


def bench_slo(rates=(40.0, 120.0, 360.0, 720.0), n_requests=36, seed=0,
              ttft_ms=50.0, tpot_ms=25.0, max_batch=8, page_size=16,
              out_path=None, target_url=None):
    """Open-loop SLO sweep (docs/observability.md "Serving SLO"): fixed
    Poisson arrival schedules at ``rates`` offered req/s drive the REAL
    HTTP server end to end (POST /v1/generate per request), and each
    rate reports TTFT / TPOT / queue-wait / e2e p50+p99 with SLO
    attainment and burn rate — the capacity-vs-SLO curve the autoscaler
    and disaggregation work will be judged with.

    Method guards:

    * **Open loop.**  Every schedule is fixed before its run (seeded
      Poisson arrivals, per-tenant prompt/output mixes, shared
      prefixes); requests fire at their absolute scheduled instant
      whether or not earlier ones completed — queueing under overload
      lands in the latencies instead of vanishing into a coordinated-
      omission feedback loop.
    * **Steady state.**  Each rate's schedule runs twice UNTIMED first
      (pass 1 mints every compiled shape and fills the prefix cache;
      pass 2 reaches the steady-state hit pattern whose continuation
      buckets the timed pass will use), then once timed.
    * **Zero recompiles.**  The timed pass runs under
      ``compile_watch.expect_no_compiles`` — a compile mid-measurement
      invalidates the row and fails the artifact.
    * **Server-side truth.**  Latencies come from the request-lifecycle
      timelines (``SloTracker``), scoped to the timed window; the
      client-observed e2e and scheduling fidelity (send lag) ride
      alongside from the load generator.

    ``target_url`` points the SAME schedules at an EXTERNAL target —
    a single replica's front end or the disaggregated router's
    (``bench.py --slo-url http://host:port``) — instead of building a
    local server; rows then carry the client-side aggregation only
    (no in-process timeline access).
    """
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.serving import (
        Server, SloPolicy, TenantConfig, TenantLoad, poisson_schedule,
        run_open_loop,
    )
    from ml_trainer_tpu.serving.slo import aggregate_timelines
    from ml_trainer_tpu.telemetry import compile_watch

    model = get_model("gpt2_tiny", max_len=256)
    variables = jax.jit(model.init, static_argnames="train")(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32),
        train=False,
    )
    policy = SloPolicy(ttft_ms=ttft_ms, tpot_ms=tpot_ms, target=0.9)
    # Production-shaped mix: a heavier "pro" tenant whose requests open
    # with a shared system prompt (prefix-cache reuse), a lighter fully
    # unique "free" tenant.
    load = {
        "pro": TenantLoad(weight=2.0, prompt_len=(8, 24),
                          output_len=(4, 16), shared_prefix_len=32,
                          shared_frac=0.6),
        "free": TenantLoad(weight=1.0, prompt_len=(8, 24),
                           output_len=(4, 16)),
    }
    tenant_cfg = {"pro": TenantConfig(weight=2.0),
                  "free": TenantConfig(weight=1.0)}
    compile_watch.install()
    rows = []
    for i, rate in enumerate(rates):
        schedule = poisson_schedule(
            float(rate), n_requests, model.vocab_size, tenants=load,
            seed=seed + i,
        )
        if target_url is not None:
            # External target (single replica or router): same recorded
            # schedule, client-side truth only.
            for _ in range(2):
                run_open_loop(schedule, url=target_url, time_scale=0.0)
            client = run_open_loop(schedule, url=target_url)
            client.pop("per_request")
            rows.append({
                "offered_rps": float(rate),
                "n_requests": n_requests,
                "tokens_per_sec": client["tokens_per_sec"],
                "n_errors": client["n_errors"],
                "client": client,
                "target_url": target_url,
                "zero_recompiles": True,  # not observable externally
            })
            print(
                f"# slo rate {rate:>6.1f} rps -> {target_url}: "
                f"{client['tokens_per_sec']:,.1f} tokens/s, client e2e "
                f"p99 {client['client_e2e_p99_ms']} ms",
                flush=True,
            )
            continue
        with Server(model, variables, max_batch=max_batch,
                    max_queue=2 * n_requests, kv_page_size=page_size,
                    tenants=dict(tenant_cfg), slo=policy,
                    slo_timelines=4 * n_requests) as srv:
            host, port = srv.serve_http(port=0)
            url = f"http://{host}:{port}"
            # Two untimed passes: compiles + prefix cache to steady
            # state (pass 2's hit pattern == the timed pass's).
            for _ in range(2):
                run_open_loop(schedule, url=url, time_scale=0.0)
            timed_t0 = time.monotonic()
            err = None
            try:
                with compile_watch.expect_no_compiles(f"slo rate {rate}"):
                    client = run_open_loop(schedule, url=url)
            except AssertionError as e:
                err = str(e)
                client = run_open_loop(schedule, url=url)
            server_side = aggregate_timelines(
                srv.slo.timelines(since=timed_t0), policy
            )
            snap = srv.metrics.snapshot()
        client.pop("per_request")
        row = {
            "offered_rps": float(rate),
            "n_requests": n_requests,
            "tokens_per_sec": client["tokens_per_sec"],
            "n_errors": client["n_errors"],
            "client": client,
            "server": server_side,
            "prefix_hit_rate": snap["prefix_hit_rate"],
            "preemptions": snap["preemptions_total"],
            "zero_recompiles": err is None,
        }
        if err is not None:
            row["recompile_error"] = err
        rows.append(row)
        print(
            f"# slo rate {rate:>6.1f} rps: {row['tokens_per_sec']:,.1f} "
            f"tokens/s, TTFT p99 {server_side['ttft_ms']['p99']} ms, "
            f"TPOT p99 {server_side['tpot_ms']['p99']} ms, attainment "
            f"ttft={server_side['attainment']['ttft']} "
            f"tpot={server_side['attainment']['tpot']}"
            + ("" if err is None else "  [RECOMPILED]"),
            flush=True,
        )
    result = {
        "policy": {"ttft_ms": ttft_ms, "tpot_ms": tpot_ms,
                   "target": policy.target},
        "rates": rows,
        "n_requests_per_rate": n_requests,
        "max_batch": max_batch,
        "page_size": page_size,
        "seed": seed,
        "zero_recompiles": all(r["zero_recompiles"] for r in rows),
        "backend": jax.default_backend(),
    }
    if not result["zero_recompiles"]:
        result["error"] = "compiles observed during a timed pass"
    if out_path:
        with open(out_path, "w", encoding="utf-8") as fp:
            json.dump(result, fp, indent=1)
        print(f"# slo artifact -> {out_path}", flush=True)
    return result


def bench_serve_lora(n_adapters=64, n_requests=96, rate_rps=400.0,
                     max_batch=8, page_size=16, rank=8, seed=0,
                     out_path=None, target_url=None):
    """Batched-LoRA serving leg (docs/serving.md "Batched LoRA
    adapters"): ``n_adapters`` concurrent adapters over ONE gpt2 base,
    open-loop at saturating load through the real HTTP server, vs the
    single-model baseline on the identical schedule.

    Method guards:

    * **Identical traffic.**  One seeded Poisson schedule whose
      requests draw uniformly from {base, adapter_00..} plus a shared
      system prefix; the baseline server runs the SAME schedule with
      every adapter field stripped — so the ratio prices exactly the
      per-row gather + low-rank delta, not a workload difference.
    * **Byte identity.**  Every ``adapter=None`` request's output on
      the LoRA server must equal the baseline server's output for the
      same request (the trash-slot-0 zero-delta contract).
    * **Hot-load mid-run.**  A brand-new adapter registers and serves
      DURING the timed pass, inside ``compile_watch.expect_no_compiles``
      — the one warm upload program plus the rank bucket make the load
      a pure data movement.
    * **Mixed ranks.**  Adapters alternate trained rank ``rank/2`` and
      ``rank`` (zero-padded into the one bucket), so the zero-recompile
      pin covers the mixed-rank case.

    ``target_url`` points the same schedule at an EXTERNAL target
    (``bench.py --serve-lora-url http://host:port`` — e.g. a router
    fleet built with adapter pools); rows then carry client-side truth
    only and no artifact is written.
    """
    import os
    import tempfile

    from ml_trainer_tpu.lora import LoraConfig, export_lora_artifact
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.serving import (
        AdapterConfig, Server, TenantLoad, poisson_schedule,
        run_open_loop,
    )
    from ml_trainer_tpu.telemetry import compile_watch

    # gpt2_mini (512-wide): wide enough that a rank-8 delta is the
    # production-shaped small fraction of the base matmul — on the
    # 128-wide test config the gather+delta is a third of the whole
    # step and the ratio measures the toy width, not the design.
    model = get_model("gpt2_mini", max_len=256)
    variables = jax.jit(model.init, static_argnames="train")(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32),
        train=False,
    )
    targets = ("qkv", "proj")
    names = [f"a{i:02d}" for i in range(n_adapters - 1)]

    # Fabricate adapter artifacts: train-mode init (A small, B zeros)
    # with B given real mass, alternating trained ranks — small enough
    # that tokens stay plausible, large enough that outputs differ.
    tmp = tempfile.mkdtemp(prefix="bench_lora_")
    rng = np.random.default_rng(seed)

    def make_artifact(name, r, scale=0.5):
        lm = model.clone(lora_rank=r, lora_alpha=float(2 * r),
                         lora_targets=targets)
        params = jax.device_get(lm.init(
            {"params": jax.random.PRNGKey(1)},
            np.zeros((1, 8), np.int32), train=False,
        )["params"])

        def bump(node):
            out = {}
            for k, v in node.items():
                if hasattr(v, "items"):
                    out[k] = bump(v)
                elif "_lora_B" in k:
                    out[k] = rng.standard_normal(
                        v.shape
                    ).astype(np.float32) * scale
                else:
                    out[k] = v
            return out

        path = os.path.join(tmp, f"{name}.npz")
        export_lora_artifact(
            bump(dict(params)),
            LoraConfig(rank=r, alpha=float(2 * r), targets=targets),
            path, name=name,
        )
        return path

    sources = {
        n: make_artifact(n, rank if i % 2 else rank // 2)
        for i, n in enumerate(names)
    }
    hot_path = make_artifact("hot", rank)

    # ~20% base traffic interleaved with the adapter mix; the first
    # len(names) arrivals are then pinned to cover EVERY adapter once,
    # so the pool genuinely holds n_adapters concurrent residents.
    # shared_frac is modest: per-adapter prefix namespacing (correct by
    # construction — K/V is adapter-specific) means 64-way traffic
    # cannot share the system prefix the way one model can, and the
    # ratio should price the GATHER, not mostly that hit-rate delta.
    mix = TenantLoad(
        weight=1.0, prompt_len=(8, 24), output_len=(4, 16),
        shared_prefix_len=16, shared_frac=0.25,
        adapters=(None,) * (len(names) // 4) + tuple(names),
    )
    schedule = poisson_schedule(
        float(rate_rps), n_requests, model.vocab_size,
        tenants={"mix": mix}, seed=seed,
    )
    import dataclasses as _dc

    schedule = [
        _dc.replace(s, adapter=names[i]) if i < len(names) else s
        for i, s in enumerate(schedule)
    ]
    base_schedule = [_dc.replace(s, adapter=None) for s in schedule]

    if target_url is not None:
        for _ in range(2):
            run_open_loop(schedule, url=target_url, time_scale=0.0)
        client = run_open_loop(schedule, url=target_url)
        client.pop("per_request")
        return {
            "target_url": target_url,
            "n_adapters": n_adapters,
            "tokens_per_sec": client["tokens_per_sec"],
            "n_errors": client["n_errors"],
            "client": client,
        }

    def serve(schedule_, srv):
        host, port = srv.serve_http(port=0)
        url = f"http://{host}:{port}"
        for _ in range(2):          # compiles + prefix cache + adapter
            run_open_loop(schedule_, url=url, time_scale=0.0)  # loads
        err = None
        hot_result = {}
        snap0 = srv.metrics.snapshot()

        def hot_load():
            # The hot-load protocol under live traffic: a NEVER-seen
            # adapter registers mid-pass and serves immediately.
            if srv.engine.adapters is None:
                return
            time.sleep(0.2)
            srv.load_adapter("hot", hot_path)
            p = np.asarray(schedule_[0].prompt, np.int32)
            out = srv.complete(p, 8, adapter="hot", timeout=300)
            hot_result["tokens"] = int(np.asarray(out).size - p.size)

        import threading

        try:
            with compile_watch.expect_no_compiles("lora timed pass"):
                hot = threading.Thread(target=hot_load, daemon=True)
                hot.start()
                client = run_open_loop(
                    schedule_, url=url, collect_tokens=True
                )
                hot.join(timeout=300)
        except AssertionError as e:
            err = str(e)
            client = run_open_loop(schedule_, url=url, collect_tokens=True)
        snap = srv.metrics.snapshot()
        # Device-busy tokens/s over the timed pass only (cumulative
        # counters, so delta vs the pre-pass snapshot): the engine-side
        # rate, far less noisy than client makespan on a shared
        # container — what the single-model ratio is judged on.
        d_tokens = snap["tokens_total"] - snap0["tokens_total"]
        busy0 = (
            snap0["tokens_total"] / snap0["tokens_per_sec_busy"]
            if snap0["tokens_per_sec_busy"] else 0.0
        )
        busy1 = (
            snap["tokens_total"] / snap["tokens_per_sec_busy"]
            if snap["tokens_per_sec_busy"] else 0.0
        )
        snap["timed_tokens_per_sec_busy"] = round(
            d_tokens / (busy1 - busy0), 1
        ) if busy1 > busy0 else 0.0
        return client, snap, err, hot_result

    compile_watch.install()
    with Server(model, variables, max_batch=max_batch,
                max_queue=2 * n_requests, kv_page_size=page_size) as srv:
        base_client, base_snap, base_err, _ = serve(base_schedule, srv)
    print(
        f"# serve lora single-model baseline: "
        f"{base_client['tokens_per_sec']:,.1f} tokens/s", flush=True,
    )
    with Server(model, variables, max_batch=max_batch,
                max_queue=2 * n_requests, kv_page_size=page_size,
                adapters=AdapterConfig(
                    slots=n_adapters + 2, rank=rank, targets=targets,
                    sources=sources,
                )) as srv:
        lora_client, lora_snap, lora_err, hot_result = serve(
            schedule, srv
        )
        resident = srv.health()["adapters_resident"]
    ratio = (
        lora_snap["timed_tokens_per_sec_busy"]
        / base_snap["timed_tokens_per_sec_busy"]
        if base_snap["timed_tokens_per_sec_busy"] else 0.0
    )
    print(
        f"# serve lora {n_adapters} adapters:       "
        f"{lora_snap['timed_tokens_per_sec_busy']:,.1f} busy tokens/s "
        f"vs {base_snap['timed_tokens_per_sec_busy']:,.1f} single-model "
        f"({ratio:.2f}x), {len(resident)} resident, hot-load "
        f"{'ok' if hot_result.get('tokens') else 'MISSING'}", flush=True,
    )

    # Byte identity: every adapter=None request equal across servers.
    identical = True
    n_base_rows = 0
    for s, lr, br in zip(schedule, lora_client["per_request"],
                         base_client["per_request"]):
        if s.adapter is not None:
            continue
        n_base_rows += 1
        if lr.get("output") != br.get("output"):
            identical = False
    result = {
        "n_adapters": n_adapters,
        "adapters_resident": len(resident),
        "rank_bucket": rank,
        "mixed_ranks": [rank // 2, rank],
        "targets": list(targets),
        "n_requests": n_requests,
        "offered_rps": float(rate_rps),
        "lora": {
            "tokens_per_sec": lora_client["tokens_per_sec"],
            "tokens_per_sec_busy": lora_snap["timed_tokens_per_sec_busy"],
            "client_e2e_p99_ms": lora_client["client_e2e_p99_ms"],
            "n_errors": lora_client["n_errors"],
            "adapter_hits": lora_snap["adapter_hits_total"],
            "adapter_loads": lora_snap["adapter_loads_total"],
            "adapter_evictions": lora_snap["adapter_evictions_total"],
            "adapter_pool_bytes": lora_snap["adapter_pool_bytes"],
            "prefix_hit_rate": lora_snap["prefix_hit_rate"],
        },
        "single_model": {
            "tokens_per_sec": base_client["tokens_per_sec"],
            "tokens_per_sec_busy": base_snap["timed_tokens_per_sec_busy"],
            "client_e2e_p99_ms": base_client["client_e2e_p99_ms"],
            "n_errors": base_client["n_errors"],
            "prefix_hit_rate": base_snap["prefix_hit_rate"],
        },
        "tokens_per_sec_ratio": round(ratio, 3),
        "base_requests_byte_identical": identical,
        "n_base_requests_compared": n_base_rows,
        "hot_load_tokens": hot_result.get("tokens", 0),
        "zero_recompiles": lora_err is None and base_err is None,
        "backend": jax.default_backend(),
    }
    if lora_err or base_err:
        result["recompile_error"] = lora_err or base_err
    if not identical:
        result["error"] = "adapter=None output diverged from single-model"
    elif not result["zero_recompiles"]:
        result["error"] = "compiles observed during a timed pass"
    elif not hot_result.get("tokens"):
        result["error"] = "mid-run hot-load did not serve"
    if out_path:
        with open(out_path, "w", encoding="utf-8") as fp:
            json.dump(result, fp, indent=1)
        print(f"# serve lora artifact -> {out_path}", flush=True)
    return result


def bench_serve_disagg(n_requests=48, n_tenants=3, shared_frac=0.8,
                       mean_interarrival=0.002, shared_len=160,
                       page_size=16, max_batch=4, n_prefill=2,
                       n_decode=2, seed=0, ttft_ms=1000.0,
                       tpot_ms=1000.0, pool_factor=3, out_path=None):
    """Disaggregated prefill/decode serving vs colocated at EQUAL
    replica count (serving/router.py, docs/serving.md): the same
    recorded 80%-shared-prefix trace, replayed open-loop at saturating
    load through each topology's ROUTER HTTP front end.

    * **Disaggregated**: ``n_prefill`` prefill + ``n_decode`` decode
      replicas; every request prefills on an affinity-hashed prefill
      replica, its KV migrates at page granularity to the least-loaded
      decode replica.  Prefill slots turn over in one prefill's time,
      so TTFT stops queueing behind other requests' decode residency —
      the p99 TTFT win this artifact pins.
    * **Colocated**: ``n_prefill + n_decode`` replicas serving both
      roles behind the same router (no migration) — the equal-count
      baseline.

    Method guards (the bench_slo discipline): the trace is FIXED before
    any run (seeded, round-tripped through the recorded-trace format so
    both topologies replay identical bytes), each topology runs the
    trace twice untimed (compiles incl. the kv export/import programs +
    prefix caches to steady state) then once timed under
    ``compile_watch.expect_no_compiles``; TTFT truth comes from the
    ROUTER's request-lifecycle timelines scoped to the timed window;
    and every request's full output ids are collected and compared
    between topologies — zero byte-identity regressions is a hard
    invariant of the artifact."""
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.serving import Router, SloPolicy
    from ml_trainer_tpu.serving.loadgen import (
        ScheduledRequest, run_open_loop, schedule_from_trace,
        schedule_to_records,
    )
    from ml_trainer_tpu.serving.slo import aggregate_timelines
    from ml_trainer_tpu.telemetry import compile_watch

    model = get_model("gpt2_tiny", max_len=256)
    variables = jax.jit(model.init, static_argnames="train")(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32),
        train=False,
    )
    rng = np.random.default_rng(seed)
    prefixes = [
        rng.integers(0, model.vocab_size, shared_len).astype(np.int32)
        for _ in range(n_tenants)
    ]
    arrivals = np.cumsum(rng.exponential(mean_interarrival, n_requests))
    trace = []
    for i in range(n_requests):
        t = int(rng.integers(0, n_tenants))
        if rng.random() < shared_frac:
            suffix = rng.integers(
                0, model.vocab_size, int(rng.integers(4, 17))
            ).astype(np.int32)
            prompt = np.concatenate([prefixes[t], suffix])
        else:
            prompt = rng.integers(
                0, model.vocab_size, int(rng.integers(16, 33))
            ).astype(np.int32)
        trace.append(ScheduledRequest(
            arrival_s=float(arrivals[i]), tenant=f"tenant{t}",
            prompt=prompt,
            max_new_tokens=int(rng.choice([6, 24], p=[0.6, 0.4])),
            # A quarter of the stream is multi-turn: sessions ride the
            # recorded trace and exercise sticky decode placement.
            session=f"sess{t}-{i % 4}" if rng.random() < 0.25 else None,
        ))
    # The recorded-trace round trip: both topologies replay these bytes.
    schedule = schedule_from_trace(schedule_to_records(trace))
    useful_tokens = sum(s.max_new_tokens for s in schedule)
    policy = SloPolicy(ttft_ms=ttft_ms, tpot_ms=tpot_ms, target=0.9)
    n_replicas = n_prefill + n_decode
    compile_watch.install()

    def run_topology(mode):
        roles = (
            ["prefill"] * n_prefill + ["decode"] * n_decode
            if mode == "disagg" else ["both"] * n_replicas
        )
        router = Router.build(
            model, variables, roles=roles, max_batch=max_batch,
            kv_page_size=page_size, max_queue=2 * n_requests,
            # Oversized pools: prefix-cache residency never evicts at
            # steady state, so every pass sees the same hit lengths —
            # the same continuation buckets — and the zero-recompile
            # pin measures scheduling, not cache-churn noise.
            kv_pages=pool_factor * max_batch * (256 // page_size) + 1,
            router_kwargs={"slo": policy,
                           "slo_timelines": 4 * n_requests},
        )
        with router:
            host, port = router.serve_http(port=0)
            url = f"http://{host}:{port}"
            # Two untimed passes: compiles (prefill buckets, decode,
            # kv export/import) + prefix caches to steady state.
            for _ in range(2):
                run_open_loop(schedule, url=url, time_scale=0.0)
            timed_t0 = time.monotonic()
            err = None
            try:
                with compile_watch.expect_no_compiles(f"disagg {mode}"):
                    client = run_open_loop(
                        schedule, url=url, collect_tokens=True
                    )
            except AssertionError as e:
                err = str(e)
                client = run_open_loop(
                    schedule, url=url, collect_tokens=True
                )
            server_side = aggregate_timelines(
                router.slo.timelines(since=timed_t0), policy
            )
            snap = router.snapshot()
        outputs = [r.get("output") for r in client["per_request"]]
        row = {
            "mode": mode,
            "replicas": len(roles),
            "tokens_per_sec": client["tokens_per_sec"],
            "makespan_s": client["makespan_s"],
            "n_errors": client["n_errors"],
            "ttft_p50_ms": server_side["ttft_ms"]["p50"],
            "ttft_p99_ms": server_side["ttft_ms"]["p99"],
            "tpot_p99_ms": server_side["tpot_ms"]["p99"],
            "e2e_p99_ms": server_side["e2e_ms"]["p99"],
            "attainment": server_side["attainment"],
            "n_timelines": server_side["n_requests"],
            "migrations": snap["migrations_total"],
            "kv_migrated_bytes": snap["kv_migrated_bytes_total"],
            "redistributes": snap["redistributes_total"],
            "zero_recompiles": err is None,
        }
        if err is not None:
            row["recompile_error"] = err
        print(
            f"# serve disagg [{mode:>9}]: {row['tokens_per_sec']:,.1f} "
            f"tokens/s, TTFT p50 {row['ttft_p50_ms']} ms / p99 "
            f"{row['ttft_p99_ms']} ms, {row['migrations']} migration(s)"
            + ("" if err is None else "  [RECOMPILED]"),
            flush=True,
        )
        return row, outputs

    disagg, disagg_outs = run_topology("disagg")
    coloc, coloc_outs = run_topology("colocated")
    identical = (
        all(o is not None for o in disagg_outs + coloc_outs)
        and all(a == b for a, b in zip(disagg_outs, coloc_outs))
    )
    ratio = (
        round(disagg["ttft_p99_ms"] / coloc["ttft_p99_ms"], 3)
        if coloc["ttft_p99_ms"] else None
    )
    result = {
        "disagg": disagg,
        "colocated": coloc,
        "ttft_p99_ratio": ratio,
        "ttft_win": bool(ratio is not None and ratio < 1.0),
        "byte_identical": identical,
        "zero_recompiles": bool(
            disagg["zero_recompiles"] and coloc["zero_recompiles"]
        ),
        "n_requests": n_requests,
        "n_tenants": n_tenants,
        "shared_frac": shared_frac,
        "shared_len": shared_len,
        "page_size": page_size,
        "max_batch": max_batch,
        "n_prefill": n_prefill,
        "n_decode": n_decode,
        "useful_tokens": useful_tokens,
        "seed": seed,
        "backend": jax.default_backend(),
    }
    if not identical:
        result["error"] = "disaggregated output diverged from colocated"
    elif not result["zero_recompiles"]:
        result["error"] = "compiles observed during a timed pass"
    elif disagg["n_errors"] or coloc["n_errors"]:
        result["error"] = (
            f"client errors: disagg {disagg['n_errors']}, colocated "
            f"{coloc['n_errors']}"
        )
    elif not result["ttft_win"]:
        result["error"] = (
            f"disaggregated p99 TTFT did not beat colocated "
            f"(ratio {ratio})"
        )
    if out_path:
        with open(out_path, "w", encoding="utf-8") as fp:
            json.dump(result, fp, indent=1)
        print(f"# serve disagg artifact -> {out_path}", flush=True)
    return result


def bench_serve_fleet(n_requests=32, n_tenants=2, long_frac=0.4,
                      mean_interarrival=0.05, long_len=176,
                      short_hi=24, page_size=16, max_batch=4,
                      prefill_chunk=64, pool_factor=3, seed=0,
                      ttft_ms=1000.0, tpot_ms=1000.0, out_path=None):
    """True multi-process serving fleet (serving/fleet.py,
    docs/serving.md "Multi-process fleet"): every replica its own OS
    process, the router driving them ONLY over HTTP sockets, KV
    migration as real serialized bytes CRC-verified at the receiving
    process.  Four legs, one committed artifact:

    * **fleet** — a 4-process fleet (2 prefill + 2 decode, chunked
      prefill at ``prefill_chunk``) replays a seeded long+short mix
      open-loop through the router front end: every output
      byte-identical to in-driver ``generate()``, zero post-warmup
      compiles PER REPLICA PROCESS (each worker's ``compile_watch``
      count via ``/v1/spec`` before/after the timed pass), migrations
      metered in socket bytes.
    * **short_only** — the same fleet replaying an all-short trace:
      context for how much of the mix's latency is the long prompts
      themselves (``mix_vs_short_tokens_ratio``).
    * **unchunked** — a second fleet with ``prefill_chunk=0`` replaying
      the SAME mix — the controlled comparison (identical workload,
      identical processes, only the chunking knob differs): long
      prompts head-of-line-block short requests' TTFT inside
      monolithic prefills; the ``chunked_ttft_ratio`` (chunked /
      unchunked short-request p99 TTFT, win <= 1.0) pins the
      HOL-blocking win, and ``chunked_tokens_ratio`` (chunked /
      unchunked mix tokens/s, floor 0.9) pins that the per-window
      dispatch overhead does not tax throughput.  Arrivals come in
      longs-first bursts at a non-saturating rate, so every short
      request contends with an in-flight long prefill by construction
      — under saturated Poisson arrivals TTFT measures queue drain,
      and at low rates a short only collides with a ~10 ms monolithic
      prefill by luck.
    * **chaos** — a REAL ``SIGKILL`` of a decode worker mid-stream:
      every in-flight stream redistributes byte-identical, and the
      SLO-burn autoscaler respawns a real replacement process.

    Method guards as in ``bench_serve_disagg``: the traces are fixed
    (seeded + recorded-trace round trip) before any run; each fleet
    replays each trace twice untimed (workers compile to steady state
    against the shared on-disk cache) before its timed pass.  Workers
    run with the prefix cache OFF and the router with hedging OFF —
    replayed traces must genuinely re-prefill (else the timed pass is
    all prefix hits and chunking never engages) and placement must be
    deterministic across passes (hedge duplicates compile fresh
    buckets on whichever replica straggles that run)."""
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.serving import Autoscaler, AutoscalerConfig
    from ml_trainer_tpu.serving.fleet import Fleet
    from ml_trainer_tpu.serving import SloPolicy
    from ml_trainer_tpu.serving.loadgen import (
        ScheduledRequest, run_open_loop, schedule_from_trace,
        schedule_to_records,
    )
    from ml_trainer_tpu.serving.slo import aggregate_timelines
    from ml_trainer_tpu.generate import generate

    max_len = 256
    model = get_model("gpt2_tiny", max_len=max_len)
    variables = jax.jit(model.init, static_argnames="train")(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32),
        train=False,
    )
    rng = np.random.default_rng(seed)

    def make_trace(frac_long):
        # Burst arrivals, longs first within each burst: every short
        # request lands WHILE a long prefill is in flight on its
        # prefill replica, so the TTFT comparison below measures
        # head-of-line blocking by construction (Poisson arrivals at a
        # rate low enough to avoid queue-drain TTFT only collide a
        # short with a ~10 ms monolithic prefill by luck).
        burst = 4
        n_long = int(round(burst * frac_long)) if frac_long else 0
        rows = []
        for i in range(n_requests):
            b, j = divmod(i, burst)
            is_long = j < n_long
            if is_long:
                n = int(rng.integers(long_len - 16, long_len + 17))
            else:
                n = int(rng.integers(8, short_hi + 1))
            rows.append(ScheduledRequest(
                arrival_s=float(
                    b * burst * mean_interarrival + j * 1e-4
                ),
                tenant=f"tenant{i % n_tenants}",
                prompt=rng.integers(
                    0, model.vocab_size, n
                ).astype(np.int32),
                max_new_tokens=int(rng.choice([8, 20], p=[0.4, 0.6])),
            ))
        return schedule_from_trace(schedule_to_records(rows))

    trace_mix = make_trace(long_frac)
    trace_short = make_trace(0.0)
    refs = {
        id(tr): [
            [int(t) for t in np.asarray(
                generate(model, variables, s.prompt[None],
                         s.max_new_tokens)
            )[0]]
            for s in tr
        ]
        for tr in (trace_mix, trace_short)
    }
    policy = SloPolicy(ttft_ms=ttft_ms, tpot_ms=tpot_ms, target=0.9)
    kv_pages = pool_factor * max_batch * (max_len // page_size) + 1

    def worker_compiles(fleet):
        out = {}
        for name, rep in fleet.replicas.items():
            try:
                out[name] = int(rep._get("/v1/spec")["compiles"] or 0)
            except Exception:
                out[name] = None
        return out

    def timed_pass(fleet, router, url, trace, mode, short_max=None):
        before = worker_compiles(fleet)
        chunks_before = 0
        for rep in fleet.replicas.values():
            try:
                chunks_before += int(rep._get("/metrics.json").get(
                    "prefill_chunks_total", 0
                ))
            except Exception:
                pass
        timed_t0 = time.monotonic()
        client = run_open_loop(trace, url=url, collect_tokens=True)
        after = worker_compiles(fleet)
        tls = router.slo.timelines(since=timed_t0)
        agg = aggregate_timelines(tls, policy)
        short_agg = None
        if short_max is not None:
            short_tls = [
                tl for tl in tls
                if tl.get("prompt_tokens") is not None
                and tl["prompt_tokens"] <= short_max
            ]
            short_agg = aggregate_timelines(short_tls, policy)
        chunks_after = 0
        for rep in fleet.replicas.values():
            try:
                chunks_after += int(rep._get("/metrics.json").get(
                    "prefill_chunks_total", 0
                ))
            except Exception:
                pass
        identical = all(
            r.get("output") == ref
            for r, ref in zip(client["per_request"], refs[id(trace)])
        )
        fresh = {
            n: (after[n] - before[n])
            if before.get(n) is not None and after.get(n) is not None
            else None
            for n in after
        }
        snap = router.snapshot()
        row = {
            "mode": mode,
            "tokens_per_sec": client["tokens_per_sec"],
            "makespan_s": client["makespan_s"],
            "n_errors": client["n_errors"],
            "ttft_p50_ms": agg["ttft_ms"]["p50"],
            "ttft_p99_ms": agg["ttft_ms"]["p99"],
            "byte_identical": identical,
            "migrations": snap["migrations_total"],
            "kv_migrated_bytes": snap["kv_migrated_bytes_total"],
            "prefill_chunks": chunks_after - chunks_before,
            "worker_compiles_timed": fresh,
            "zero_recompiles": all(v == 0 for v in fresh.values()),
        }
        if short_agg is not None:
            row["short_ttft_p50_ms"] = short_agg["ttft_ms"]["p50"]
            row["short_ttft_p99_ms"] = short_agg["ttft_ms"]["p99"]
            row["short_n"] = short_agg["n_requests"]
        print(
            f"# serve fleet [{mode:>10}]: {row['tokens_per_sec']:,.1f} "
            f"tokens/s, TTFT p99 {row['ttft_p99_ms']} ms"
            + (f" (short p99 {row.get('short_ttft_p99_ms')} ms)"
               if short_agg is not None else "")
            + f", {row['prefill_chunks']} chunk(s)"
            + ("" if row["zero_recompiles"] else "  [RECOMPILED]"),
            flush=True,
        )
        return row

    def run_fleet(chunk, legs):
        fleet = Fleet(
            roles=["prefill", "prefill", "decode", "decode"],
            model_name="gpt2_tiny", max_len=max_len,
            max_batch=max_batch, max_queue=2 * n_requests,
            kv_page_size=page_size, kv_pages=kv_pages, seed=0,
            prefill_chunk=chunk,
            # The prefix cache would turn the replayed traces into full
            # prefix hits after warmup, so the timed pass would never
            # exercise chunked prefill (and the chunked-vs-monolithic
            # TTFT comparison would measure cache lookups, not
            # prefills).  Hedging is off for the same reason: hedge
            # duplicates land on whichever replica is slow THAT run,
            # compiling fresh buckets mid-timed-pass.
            prefix_cache=False,
        )
        fleet.start()
        router = fleet.make_router(
            slo=policy, slo_timelines=4 * n_requests, hedging=False,
        )
        rows = {}
        chaos = None
        try:
            host, port = router.serve_http(port=0)
            url = f"http://{host}:{port}"
            warmed = set()
            for tr, _, _ in legs:
                if id(tr) in warmed:
                    continue
                warmed.add(id(tr))
                for _ in range(2):  # untimed: workers compile
                    run_open_loop(tr, url=url, time_scale=0.0)
            for tr, mode, short_max in legs:
                rows[mode] = timed_pass(
                    fleet, router, url, tr, mode, short_max=short_max
                )
            if chunk:  # chaos leg rides the chunked fleet
                chaos = chaos_leg(fleet, router)
        finally:
            router.close()
            fleet.stop()
        return rows, chaos

    def chaos_leg(fleet, router):
        subset = [s for s in trace_mix[:8]]
        c_refs = [
            [int(t) for t in np.asarray(
                generate(model, variables, s.prompt[None],
                         s.max_new_tokens)
            )[0]]
            for s in subset
        ]
        streams = [
            router.submit(s.prompt, s.max_new_tokens) for s in subset
        ]
        deadline = time.monotonic() + 120
        while any(len(s.tokens) < 2 for s in streams):
            if time.monotonic() > deadline:
                return {"error": "chaos streams never started decoding"}
            time.sleep(0.02)
        victim = fleet.replicas["decode0"]
        kill_t0 = time.monotonic()
        fleet.kill("decode0")
        autoscaler = Autoscaler(
            router, fleet.factory,
            AutoscalerConfig(poll_interval_s=0.2, min_prefill=2,
                             min_decode=2, replace_cooldown_s=0.2),
        ).start()
        try:
            outs = [
                [int(t) for t in np.asarray(s.result(timeout=300))]
                for s in streams
            ]
            identical = outs == c_refs
            respawn_s = None
            new_pid = None
            while time.monotonic() < deadline + 180:
                fresh = [
                    r for r in router.replicas.values()
                    if r.healthy and not r.removing
                    and r.name.startswith("auto")
                ]
                if fresh:
                    respawn_s = round(time.monotonic() - kill_t0, 3)
                    new_pid = fresh[0].server.pid
                    break
                time.sleep(0.1)
        finally:
            autoscaler.close()
        snap = router.snapshot()
        return {
            "killed_pid": victim.pid,
            "respawned_pid": new_pid,
            "respawn_s": respawn_s,
            "redistributes": snap["redistributes_total"],
            "byte_identical": identical,
        }

    chunked_rows, chaos = run_fleet(prefill_chunk, [
        (trace_mix, "fleet", short_hi),
        (trace_short, "short_only", None),
    ])
    unchunked_rows, _ = run_fleet(0, [
        (trace_mix, "unchunked", short_hi),
    ])
    fleet_row = chunked_rows["fleet"]
    short_row = chunked_rows["short_only"]
    unchunked = unchunked_rows["unchunked"]
    ttft_ratio = (
        round(fleet_row["short_ttft_p99_ms"]
              / unchunked["short_ttft_p99_ms"], 3)
        if unchunked.get("short_ttft_p99_ms") else None
    )
    tokens_ratio = (
        round(fleet_row["tokens_per_sec"]
              / unchunked["tokens_per_sec"], 3)
        if unchunked["tokens_per_sec"] else None
    )
    mix_vs_short = (
        round(fleet_row["tokens_per_sec"]
              / short_row["tokens_per_sec"], 3)
        if short_row["tokens_per_sec"] else None
    )
    rows = [fleet_row, short_row, unchunked]
    result = {
        "fleet": fleet_row,
        "short_only": short_row,
        "unchunked": unchunked,
        "chaos": chaos,
        "chunked_ttft_ratio": ttft_ratio,
        "chunked_tokens_ratio": tokens_ratio,
        "mix_vs_short_tokens_ratio": mix_vs_short,
        "ttft_win": bool(ttft_ratio is not None and ttft_ratio <= 1.0),
        "tokens_floor": bool(
            tokens_ratio is not None and tokens_ratio >= 0.9
        ),
        "byte_identical": bool(
            all(r["byte_identical"] for r in rows)
            and chaos is not None and chaos.get("byte_identical")
        ),
        "zero_recompiles": all(r["zero_recompiles"] for r in rows),
        "n_requests": n_requests,
        "long_frac": long_frac,
        "long_len": long_len,
        "page_size": page_size,
        "max_batch": max_batch,
        "prefill_chunk": prefill_chunk,
        "seed": seed,
        "backend": jax.default_backend(),
    }
    if not result["byte_identical"]:
        result["error"] = "fleet output diverged from generate()"
    elif not result["zero_recompiles"]:
        result["error"] = "worker compiles observed during a timed pass"
    elif any(r["n_errors"] for r in rows):
        result["error"] = (
            f"client errors: {[r['n_errors'] for r in rows]}"
        )
    elif fleet_row["prefill_chunks"] < 1:
        result["error"] = "chunked prefill never engaged on the mix"
    elif chaos is None or chaos.get("respawned_pid") is None:
        result["error"] = "autoscaler never respawned the killed worker"
    elif not result["ttft_win"]:
        result["error"] = (
            f"chunked prefill did not hold short-request p99 TTFT "
            f"(ratio {ttft_ratio})"
        )
    elif not result["tokens_floor"]:
        result["error"] = (
            f"chunked prefill taxed mix tokens/s below 0.9x the "
            f"unchunked fleet (ratio {tokens_ratio})"
        )
    if out_path:
        with open(out_path, "w", encoding="utf-8") as fp:
            json.dump(result, fp, indent=1)
        print(f"# serve fleet artifact -> {out_path}", flush=True)
    return result


def bench_fleet_obs(n_requests=12, n_tenants=2, mean_interarrival=0.02,
                    page_size=16, max_batch=2, pool_factor=3, seed=0,
                    scrape_iters=20, out_path=None):
    """Fleet observability plane (serving/router.py "fleet plane",
    docs/observability.md "Fleet plane") measured on a REAL 3-process
    fleet: the cost of watching the fleet, plus the invariants that
    make the watching trustworthy.  One committed artifact
    (docs/fleet_obs_cpu.json):

    * **overhead** — wall-clock for one federated ``/metrics`` scrape
      sweep (router pulls every worker's exposition over HTTP), one
      federated render (relabel + merge into the router's own
      exposition), one fleet trace merge (``GET /trace`` from every
      worker, clock-align, merge into a single Perfetto timeline), and
      one full incident-bundle assembly.  All host-side, all off the
      request path — the numbers bound what the plane costs the router
      thread, not the workers.
    * **federation invariants** — every worker series appears in the
      federated exposition carrying ``replica=``/``role=``/
      ``generation=`` labels, including each worker's
      ``compile_events_post_warmup_total`` (rendered at 0, so absence
      means "watch missing", never "no recompile yet"); a re-scrape +
      re-render is byte-identical on the worker sections (snapshots
      replace — histograms cannot double-count).
    * **trace invariants** — the merged timeline holds >= 2 process
      lanes and a migrated request whose prefill-side fragment (on the
      prefill worker's lane) ends before its decode-side span (on a
      DIFFERENT pid's lane) begins, after clock alignment.
    * **plane-is-free invariants** — with the plane fully enabled
      (scraping, tracing, bundling), the replayed trace stays
      byte-identical to in-driver ``generate()`` and every worker
      reports zero post-warmup compiles; loadgen rows carry the
      serving replica id.
    """
    import os
    import tempfile

    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.serving.fleet import Fleet
    from ml_trainer_tpu.serving.loadgen import (
        ScheduledRequest, run_open_loop, schedule_from_trace,
        schedule_to_records,
    )
    from ml_trainer_tpu.generate import generate

    max_len = 128
    model = get_model("gpt2_tiny", max_len=max_len)
    variables = jax.jit(model.init, static_argnames="train")(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32),
        train=False,
    )
    rng = np.random.default_rng(seed)
    rows = [
        ScheduledRequest(
            arrival_s=i * mean_interarrival,
            tenant=f"tenant{i % n_tenants}",
            prompt=rng.integers(
                0, model.vocab_size, int(rng.integers(8, 25))
            ).astype(np.int32),
            max_new_tokens=int(rng.choice([6, 10])),
        )
        for i in range(n_requests)
    ]
    trace = schedule_from_trace(schedule_to_records(rows))
    refs = [
        [int(t) for t in np.asarray(
            generate(model, variables, s.prompt[None], s.max_new_tokens)
        )[0]]
        for s in trace
    ]
    kv_pages = pool_factor * max_batch * (max_len // page_size) + 1

    def worker_compiles(fleet):
        out = {}
        for name, rep in fleet.replicas.items():
            try:
                out[name] = int(rep._get("/v1/spec")["compiles"] or 0)
            except Exception:
                out[name] = None
        return out

    def _ms(samples):
        if not samples:
            return None
        s = sorted(samples)
        return {
            "mean_ms": round(sum(s) / len(s) * 1e3, 3),
            "p50_ms": round(s[len(s) // 2] * 1e3, 3),
            "max_ms": round(s[-1] * 1e3, 3),
            "n": len(s),
        }

    def worker_lines(text):
        # The federated exposition's worker sections: every sample line
        # that carries a replica= label (router-own series do not).
        return [
            ln for ln in text.splitlines()
            if ln and not ln.startswith("#") and 'replica="' in ln
        ]

    fleet = Fleet(
        roles=["prefill", "decode", "decode"], model_name="gpt2_tiny",
        max_len=max_len, max_batch=max_batch, max_queue=4 * n_requests,
        kv_page_size=page_size, kv_pages=kv_pages, seed=0,
        prefix_cache=False,
    )
    fleet.start()
    incident_root = tempfile.mkdtemp(prefix="fleet-obs-incident-")
    router = fleet.make_router(
        hedging=False, metrics_scrape_interval=0.1,
        incident_dir=incident_root, incident_min_interval_s=0.0,
    )
    result = {
        "n_requests": n_requests,
        "page_size": page_size,
        "max_batch": max_batch,
        "seed": seed,
        "backend": jax.default_backend(),
    }
    try:
        host, port = router.serve_http(port=0)
        url = f"http://{host}:{port}"
        for _ in range(2):  # untimed: workers compile to steady state
            run_open_loop(trace, url=url, time_scale=0.0)
        before = worker_compiles(fleet)
        client = run_open_loop(trace, url=url, collect_tokens=True)
        after = worker_compiles(fleet)
        fresh = {
            n: (after[n] - before[n])
            if before.get(n) is not None and after.get(n) is not None
            else None
            for n in after
        }
        identical = all(
            r.get("output") == ref
            for r, ref in zip(client["per_request"], refs)
        )
        rows_with_replica = sum(
            1 for r in client["per_request"] if r.get("replica")
        )

        # Overhead: scrape sweep / federated render / trace merge.
        scrape_s, render_s = [], []
        for _ in range(scrape_iters):
            t0 = time.perf_counter()
            router.scrape_metrics(force=True)
            scrape_s.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            text = router.federated_metrics_text()
            render_s.append(time.perf_counter() - t0)
        lines_a = worker_lines(text)
        router.scrape_metrics(force=True)
        lines_b = worker_lines(router.federated_metrics_text())
        idempotent = lines_a == lines_b
        workers = sorted(fleet.replicas)
        fed_ok = all(
            any(
                ln.startswith("compile_events_post_warmup_total{")
                and f'replica="{name}"' in ln and 'role="' in ln
                and 'generation="' in ln
                for ln in lines_a
            )
            for name in workers
        )

        t0 = time.perf_counter()
        merged = router.fleet_trace()
        merge_s = time.perf_counter() - t0
        events = merged.get("traceEvents", [])
        lanes = {
            e.get("pid") for e in events if e.get("ph") != "M"
        }
        # A migrated request: its kv_wire span names the trace id; the
        # prefill fragment and decode span must sit on different lanes
        # in causal order after clock alignment.
        causal = None
        router_pid = os.getpid()  # the router's lane: its own request
        for ev in events:         # spans start at submit, pre-prefill
            name = ev.get("name", "")
            if not name.startswith("kv_wire "):
                continue
            tid = name.split(" ", 1)[1]
            pre = next(
                (e for e in events
                 if e.get("name") == f"request {tid} (prefill)"), None,
            )
            dec = next(
                (e for e in events
                 if e.get("name") == f"request {tid}"
                 and e.get("pid") not in (
                     (pre or {}).get("pid"), router_pid,
                 )), None,
            )
            if pre is None or dec is None:
                continue
            pre_end = pre["ts"] + pre.get("dur", 0.0)
            causal = {
                "trace_id": tid,
                "prefill_pid": pre["pid"],
                "decode_pid": dec["pid"],
                "gap_us": round(dec["ts"] - pre_end, 1),
                # Epoch alignment is exact on one host; allow the NTP
                # fallback's rtt/2 error bound.
                "ordered": bool(dec["ts"] >= pre_end - 5_000.0),
            }
            if causal["ordered"]:
                break

        t0 = time.perf_counter()
        bundle = router.save_incident_bundle(
            "bench_fleet_obs", force=True,
        )
        bundle_s = time.perf_counter() - t0
        bundle_files = sorted(os.listdir(bundle)) if bundle else []
        want = {"flight_router.json", "metrics.prom", "manifest.json",
                "slo_timelines.json", "router.json"}
        want |= {f"flight_{n}.json" for n in workers}
        bundle_ok = bundle is not None and want <= set(bundle_files)

        result.update({
            "scrape": _ms(scrape_s),
            "federated_render": _ms(render_s),
            "trace_merge_ms": round(merge_s * 1e3, 3),
            "bundle_assembly_ms": round(bundle_s * 1e3, 3),
            "federated_lines": len(lines_a),
            "federated_labels_ok": bool(fed_ok),
            "idempotent_rescrape": bool(idempotent),
            "trace_lanes": len(lanes),
            "trace_events": len(events),
            "migrated_request": causal,
            "fleet_clock": {
                n: {"method": c.get("method"),
                    "rtt_us": c.get("rtt_us")}
                for n, c in merged.get("fleetClock", {}).items()
            },
            "bundle_files": bundle_files,
            "bundle_ok": bool(bundle_ok),
            "rows_with_replica": rows_with_replica,
            "n_errors": client["n_errors"],
            "byte_identical": bool(identical),
            "worker_compiles_timed": fresh,
            "zero_recompiles": all(v == 0 for v in fresh.values()),
        })
    finally:
        router.close()
        fleet.stop()
    if not result.get("byte_identical"):
        result["error"] = (
            "fleet output diverged from generate() with the plane on"
        )
    elif not result.get("zero_recompiles"):
        result["error"] = "worker compiles observed during a timed pass"
    elif result.get("n_errors"):
        result["error"] = f"client errors: {result['n_errors']}"
    elif not result.get("federated_labels_ok"):
        result["error"] = (
            "federated exposition missing worker series/labels"
        )
    elif not result.get("idempotent_rescrape"):
        result["error"] = "re-scrape changed the federated worker lines"
    elif result.get("trace_lanes", 0) < 2:
        result["error"] = (
            f"merged trace holds {result.get('trace_lanes')} lane(s)"
        )
    elif not (result.get("migrated_request") or {}).get("ordered"):
        result["error"] = (
            "no migrated request in causal order across two lanes"
        )
    elif not result.get("bundle_ok"):
        result["error"] = (
            f"incident bundle incomplete: {result.get('bundle_files')}"
        )
    elif result.get("rows_with_replica", 0) < n_requests:
        result["error"] = (
            f"only {result.get('rows_with_replica')}/{n_requests} "
            "loadgen rows carried a serving replica id"
        )
    print(
        "# fleet obs: scrape "
        f"{(result.get('scrape') or {}).get('mean_ms')} ms, render "
        f"{(result.get('federated_render') or {}).get('mean_ms')} ms, "
        f"merge {result.get('trace_merge_ms')} ms "
        f"({result.get('trace_lanes')} lanes), bundle "
        f"{result.get('bundle_assembly_ms')} ms"
        + ("" if not result.get("error") else
           f"  [FAILED: {result['error']}]"),
        flush=True,
    )
    if out_path:
        with open(out_path, "w", encoding="utf-8") as fp:
            json.dump(result, fp, indent=1)
        print(f"# fleet obs artifact -> {out_path}", flush=True)
    return result


def bench_watchtower(sample_iters=200, eval_iters=200, render_iters=20,
                     n_hosts=3, out_path=None):
    """Watchtower overhead (telemetry/watchtower.py + alerts.py,
    docs/observability.md "Watchtower"): what the TSDB + alert engine
    + dashboard cost the host thread that already runs the publish
    loops, plus the invariants that make the watching trustworthy.
    One committed artifact (docs/watchtower_cpu.json):

    * **overhead** — per-call wall-clock for one full registry sample
      into the ring store (a serving-worker-sized registry: gauges,
      counters, labeled histograms), one exposition ingest (the
      router's federation path), one declarative alert-engine tick
      (threshold + rate + burn + quantile + absent rules over every
      label group), one windowed quantile query, and one dashboard
      render.  All host-side, zero device work.
    * **detection invariant** — an injected latency regression (the
      TTFT histogram's observations jump 10x) must trip the
      ``quantile_over_time`` rule on the FIRST evaluation after the
      regressed samples land: detection latency is one sample tick +
      one eval tick, never a window.
    * **storage invariants** — rings stay bounded at their capacity
      under sustained sampling, and a ``dump()`` -> ``load()``
      round-trip is exact.

    The ratcheted headline is ``sample_ops_per_sec`` (how many full
    registry sweeps one core sustains) — the number that bounds what
    the TSDB costs every publish cadence in the process.
    """
    from ml_trainer_tpu.telemetry.alerts import AlertEngine, AlertRule
    from ml_trainer_tpu.telemetry.export import prometheus_text
    from ml_trainer_tpu.telemetry.flight import FlightRecorder
    from ml_trainer_tpu.telemetry.registry import MetricsRegistry
    from ml_trainer_tpu.telemetry.watchtower import (
        TimeSeriesStore, render_dashboard,
    )

    def _ms(samples):
        if not samples:
            return None
        s = sorted(samples)
        return {
            "mean_ms": round(sum(s) / len(s) * 1e3, 3),
            "p50_ms": round(s[len(s) // 2] * 1e3, 3),
            "max_ms": round(s[-1] * 1e3, 3),
            "n": len(s),
        }

    # A serving-worker-sized registry: the per-tenant latency
    # histograms plus a spread of gauges/counters with host labels.
    registry = MetricsRegistry()
    rng = np.random.default_rng(0)
    hists = [
        registry.histogram(
            f"serving_{which}_seconds", f"{which} latency",
            labelnames=("tenant",),
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0),
        )
        for which in ("ttft", "tpot", "queue_wait", "e2e")
    ]
    for h in hists:
        for tenant in ("alpha", "beta", "gamma"):
            for v in rng.uniform(0.002, 0.04, 64):
                h.labels(tenant=tenant).observe(float(v))
    gauges = [
        registry.gauge(f"watch_gauge_{i}", f"gauge {i}",
                       labelnames=("host",))
        for i in range(24)
    ]
    counters = [
        registry.counter(f"watch_counter_{i}", f"counter {i}",
                         labelnames=("host",))
        for i in range(12)
    ]
    for h in range(n_hosts):
        for g in gauges:
            g.labels(host=str(h)).set(float(rng.uniform(0, 100)))
        for c in counters:
            c.labels(host=str(h)).inc(int(rng.integers(1, 50)))

    result = {
        "backend": jax.default_backend(),
        "n_hosts": n_hosts,
        "sample_iters": sample_iters,
    }

    # -- sampling overhead (the trainer/server publish-cadence cost) --
    store = TimeSeriesStore(capacity=256)
    sample_s = []
    t = 0.0
    for _ in range(sample_iters):
        t += 1.0
        t0 = time.perf_counter()
        store.sample_registry(registry, t=t, force=True)
        sample_s.append(time.perf_counter() - t0)
    result["sample"] = _ms(sample_s)
    result["series"] = len(store)
    result["sample_ops_per_sec"] = round(
        1.0 / max(sum(sample_s) / len(sample_s), 1e-9), 1
    )

    # -- ingest overhead (the router federation path) --
    text = prometheus_text(registry)
    ingest_store = TimeSeriesStore(capacity=256)
    ingest_s = []
    for i in range(max(sample_iters // 4, 1)):
        t0 = time.perf_counter()
        ingest_store.ingest_exposition(
            text, t=float(i),
            extra_labels={"replica": "w0", "role": "decode",
                          "generation": "0"},
            force=True,
        )
        ingest_s.append(time.perf_counter() - t0)
    result["ingest"] = _ms(ingest_s)
    result["exposition_bytes"] = len(text)

    # -- alert-engine tick + windowed-query overhead --
    flight = FlightRecorder()
    engine = AlertEngine(
        rules=[
            AlertRule("gauge_high", "watch_gauge_0 > 1e9"),
            AlertRule("counter_rate",
                      "rate(watch_counter_0[32s]) > 1e9"),
            AlertRule("burn_avg", "avg(watch_gauge_1[32s]) > 1e9",
                      for_s=5.0),
            AlertRule("ttft_q50",
                      "quantile(0.5, serving_ttft_seconds{"
                      'tenant=alpha}[32s]) > 0.2', for_count=1),
            AlertRule("absent_series", "absent(no_such_series[32s])",
                      severity="info"),
        ],
        store=store, registry=registry, flight=flight,
    )
    eval_s = []
    for i in range(eval_iters):
        t0 = time.perf_counter()
        engine.evaluate(now=t)
        eval_s.append(time.perf_counter() - t0)
    result["alert_eval"] = _ms(eval_s)
    query_s = []
    for _ in range(eval_iters):
        t0 = time.perf_counter()
        store.quantile_over_time(
            "serving_ttft_seconds", 0.5, labels={"tenant": "alpha"},
            window_s=32.0, now=t,
        )
        query_s.append(time.perf_counter() - t0)
    result["quantile_query"] = _ms(query_s)

    # -- dashboard render --
    render_s = []
    html = ""
    for _ in range(render_iters):
        t0 = time.perf_counter()
        html = render_dashboard(store, title="bench")
        render_s.append(time.perf_counter() - t0)
    result["dashboard_render"] = _ms(render_s)
    result["dashboard_bytes"] = len(html)

    # -- detection invariant: a 10x TTFT regression trips the
    # quantile rule on the first eval after the regressed samples land.
    assert not engine.rule("ttft_q50").firing()
    for v in rng.uniform(0.3, 0.5, 48):  # the regression
        hists[0].labels(tenant="alpha").observe(float(v))
    t += 1.0
    store.sample_registry(registry, t=t, force=True)
    detect_t0 = time.perf_counter()
    events = engine.evaluate(now=t)
    detect_ms = (time.perf_counter() - detect_t0) * 1e3
    fired = [
        e for e in events
        if e["rule"] == "ttft_q50" and e["state"] == "firing"
    ]
    result["detection"] = {
        "fired_first_eval": bool(fired),
        "eval_ms": round(detect_ms, 3),
        "quantile_seen": fired[0]["value"] if fired else None,
        "flight_alerts": sum(
            1 for r in flight.records() if r.get("kind") == "alert"
        ),
    }

    # -- storage invariants --
    bounded = all(
        len(points) <= 256
        for _, points in store.select("serving_ttft_seconds_bucket", {})
    ) and len(store.last("watch_gauge_0", {"host": "0"}, n=10 ** 6)) <= 256
    dump = store.dump()
    roundtrip = TimeSeriesStore.load(dump).dump() == dump
    result["ring_bounded"] = bool(bounded)
    result["dump_roundtrip_exact"] = bool(roundtrip)

    if not result["detection"]["fired_first_eval"]:
        result["error"] = (
            "injected TTFT regression did not fire the quantile rule "
            "on the first evaluation"
        )
    elif not result["ring_bounded"]:
        result["error"] = "ring exceeded its capacity under sampling"
    elif not result["dump_roundtrip_exact"]:
        result["error"] = "dump -> load round-trip not exact"
    print(
        "# watchtower: sample "
        f"{(result.get('sample') or {}).get('mean_ms')} ms "
        f"({result['series']} series, "
        f"{result['sample_ops_per_sec']} sweeps/s), ingest "
        f"{(result.get('ingest') or {}).get('mean_ms')} ms, eval "
        f"{(result.get('alert_eval') or {}).get('mean_ms')} ms, render "
        f"{(result.get('dashboard_render') or {}).get('mean_ms')} ms"
        + ("" if not result.get("error") else
           f"  [FAILED: {result['error']}]"),
        flush=True,
    )
    if out_path:
        with open(out_path, "w", encoding="utf-8") as fp:
            json.dump(result, fp, indent=1)
        print(f"# watchtower artifact -> {out_path}", flush=True)
    return result


def bench_serve_deploy(n_requests=24, n_tenants=8, mean_interarrival=0.12,
                       page_size=8, max_batch=4, seed=0,
                       ttft_ms=2000.0, tpot_ms=2000.0, wedge_s=3.0,
                       out_path=None):
    """Live base-model rollout on a multi-process fleet
    (serving/deploy.py, docs/serving.md "Deploys"): train a tiny gpt2
    in-bench, export it (manifest + weights fingerprint), then roll a
    live 2-process fleet onto the export UNDER OPEN-LOOP TRAFFIC.  Two
    legs, one committed artifact:

    * **deploy** — the fleet serves the seed init while a background
      client replays a seeded trace open-loop in a loop;
      ``Router.deploy(ckpt, canary=0.25)`` spawns new-generation
      worker PROCESSES loaded from the export (shared on-disk compile
      cache), warms them off-path, routes the tenant-hash canary slice
      at them, holds clean burn, ramps to 100% and retires the old
      workers — all while the client sees ZERO errors (no dropped
      streams) and every mid-deploy output is byte-identical to
      ``generate()`` on whichever weights its generation serves.  The
      old steady fleet's per-process compile counts (polled via
      ``/v1/spec`` until retirement) must not move during the deploy.
    * **rollback** — the SAME export deployed again (gen2 == gen1
      weights, so every output stays byte-checkable) through a wedged
      factory whose ``submit_request`` sleeps ``wedge_s`` — an honest
      TTFT regression on exactly the canary slice.  The burn watch
      trips, the deployment rolls back within one burn window, the
      fleet lands back on its pre-deploy replica set, and the client
      again sees zero errors and byte-identical outputs throughout.

    A final timed pass on the post-rollback fleet pins zero
    post-warmup recompiles + byte identity and is the throughput
    number ``gate_deploy`` ratchets."""
    import os
    import shutil
    import tempfile
    import threading

    from ml_trainer_tpu import Trainer
    from ml_trainer_tpu.checkpoint import (
        load_model_manifest, load_model_variables,
    )
    from ml_trainer_tpu.data import SyntheticTokens
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.serving import DeployConfig, SloPolicy
    from ml_trainer_tpu.serving.fleet import Fleet
    from ml_trainer_tpu.serving.loadgen import (
        ScheduledRequest, run_open_loop, schedule_from_trace,
        schedule_to_records,
    )
    from ml_trainer_tpu.generate import generate

    max_len = 64
    model = get_model("gpt2_tiny", max_len=max_len)
    rng = np.random.default_rng(seed)
    work_dir = tempfile.mkdtemp(prefix="bench_deploy_")
    ckpt_dir = os.path.join(work_dir, "export")

    # The rollout target: a REAL export of a REAL (tiny) training run,
    # manifest + weights fingerprint included.
    ds = SyntheticTokens(size=32, seq_len=16,
                         vocab_size=model.vocab_size, seed=0)
    Trainer(model, datasets=(ds, ds), epochs=1, batch_size=8,
            metric=None, model_dir=ckpt_dir, seed=7, lr=0.01).fit()
    manifest = load_model_manifest(ckpt_dir) or {}
    trained = load_model_variables(ckpt_dir)
    # Workers spawned WITHOUT --ckpt init from PRNGKey(seed=0) — the
    # driver-side twin of the old generation's weights.
    seed_vars = jax.jit(model.init, static_argnames="train")(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32),
        train=False,
    )

    policy = SloPolicy(ttft_ms=ttft_ms, tpot_ms=tpot_ms, target=0.9)
    kv_pages = 3 * max_batch * (max_len // page_size) + 1
    fleet = Fleet(
        roles=["both", "both"], model_name="gpt2_tiny", max_len=max_len,
        max_batch=max_batch, max_queue=2 * n_requests,
        kv_page_size=page_size, kv_pages=kv_pages, seed=0,
        # Prefix cache off so looped replays genuinely re-prefill and
        # stay byte-comparable; hedging off so placement (and thus
        # which generation serves a mid-deploy request) follows the
        # tenant-hash split deterministically.
        prefix_cache=False,
    )
    fleet.start()
    router = fleet.make_router(
        slo=policy, slo_timelines=8 * n_requests, hedging=False,
    )
    result = {}
    try:
        host, port = router.serve_http(port=0)
        url = f"http://{host}:{port}"

        # Tenants chosen so the 0.25 canary slice holds exactly 2 of
        # the 8 — a stable cohort with traffic on BOTH sides of the
        # split every pass.
        canary_pool = [t for t in (f"t{i}" for i in range(64))
                       if router.tenant_slice(t) < 0.25][:2]
        stable_pool = [t for t in (f"t{i}" for i in range(64))
                       if router.tenant_slice(t) >= 0.25][:n_tenants - 2]
        tenants = (canary_pool + stable_pool)

        rows = []
        for i in range(n_requests):
            n = int(rng.integers(8, 17))
            rows.append(ScheduledRequest(
                arrival_s=float(i * mean_interarrival),
                tenant=tenants[i % len(tenants)],
                prompt=rng.integers(
                    0, model.vocab_size, n
                ).astype(np.int32),
                max_new_tokens=8,
            ))
        trace = schedule_from_trace(schedule_to_records(rows))
        refs_seed = [
            [int(t) for t in np.asarray(
                generate(model, seed_vars, s.prompt[None],
                         s.max_new_tokens)
            )[0]]
            for s in trace
        ]
        refs_trained = [
            [int(t) for t in np.asarray(
                generate(model, trained, s.prompt[None],
                         s.max_new_tokens)
            )[0]]
            for s in trace
        ]

        def live_compiles():
            out = {}
            for rep in list(router.replicas.values()):
                try:
                    out[rep.name] = int(
                        rep.server._get("/v1/spec")["compiles"] or 0
                    )
                except Exception:
                    pass
            return out

        class _Poller:
            """Samples every live replica's compile count until
            stopped — old-generation workers are retired (processes
            gone) at promote, so their final counts must be caught
            in flight."""

            def __init__(self):
                self.last_seen = {}
                self._stop = threading.Event()
                self._thread = threading.Thread(
                    target=self._run, daemon=True)

            def _run(self):
                while not self._stop.is_set():
                    self.last_seen.update(live_compiles())
                    self._stop.wait(0.2)

            def __enter__(self):
                self._thread.start()
                return self

            def __exit__(self, *exc):
                self._stop.set()
                self._thread.join(timeout=5.0)

        class _Load:
            """Open-loop client looping the trace until stopped."""

            def __init__(self):
                self.passes = []
                self._stop = threading.Event()
                self._thread = threading.Thread(
                    target=self._run, daemon=True)

            def _run(self):
                while not self._stop.is_set():
                    self.passes.append(run_open_loop(
                        trace, url=url, collect_tokens=True))

            def __enter__(self):
                self._thread.start()
                return self

            def __exit__(self, *exc):
                self._stop.set()
                self._thread.join(timeout=600.0)

            def n_errors(self):
                return sum(p["n_errors"] for p in self.passes)

            def outputs_ok(self, allowed_refs):
                for p in self.passes:
                    for i, r in enumerate(p["per_request"]):
                        if not any(r.get("output") == refs[i]
                                   for refs in allowed_refs):
                            return False
                return bool(self.passes)

        for _ in range(2):  # untimed: workers compile to steady state
            run_open_loop(trace, url=url, time_scale=0.0)

        cfg = DeployConfig(
            canary=0.25, stages=(1.0,), hold_s=1.5,
            burn_threshold=2.0, high_polls=2, window_s=10.0,
            min_window_requests=2, stage_min_requests=2,
            poll_interval_s=0.3, drain_timeout_s=60.0,
        )

        def deploy_leg(mode, factory, allowed_refs):
            pre_replicas = sorted(router.replicas)
            base = live_compiles()
            t0 = time.monotonic()
            with _Poller() as poller, _Load() as load:
                dep = router.deploy(ckpt_dir, canary=cfg.canary,
                                    factory=factory, config=cfg)
                verdict = dep.wait(timeout=600.0)
                elapsed = round(time.monotonic() - t0, 3)
                dep.close()
            steady = {
                n: poller.last_seen[n] - base[n]
                for n in base if n in poller.last_seen
            }
            rep = dep.report()
            first_burn = next(
                (e["t"] for e in rep["events"]
                 if e["action"] == "burn_high"), None,
            )
            rolled_back_t = next(
                (e["t"] for e in rep["events"]
                 if e["action"] == "transition"
                 and e.get("to") == "rolled_back"), None,
            )
            rollback_s = (
                round(rolled_back_t - first_burn, 3)
                if first_burn is not None and rolled_back_t is not None
                else None
            )
            row = {
                "mode": mode,
                "state": verdict,
                "deploy_s": elapsed,
                "weights_fp": rep["weights_fp"],
                "old_weights_fp": rep["old_weights_fp"],
                "last_burn": rep["last_burn"],
                "rollback_cause": rep["rollback_cause"],
                "rollback_s": rollback_s,
                "n_client_passes": len(load.passes),
                "n_client_errors": load.n_errors(),
                "byte_identical": load.outputs_ok(allowed_refs),
                "steady_fleet_compiles": steady,
                "zero_steady_recompiles": all(
                    v == 0 for v in steady.values()),
                "replicas_before": pre_replicas,
                "replicas_after": sorted(router.replicas),
                "events": [
                    {k: e[k] for k in ("t", "action", "state")}
                    for e in rep["events"]
                ],
            }
            print(
                f"# serve deploy [{mode:>9}]: {verdict} in "
                f"{elapsed:.1f}s, {len(load.passes)} client pass(es), "
                f"{row['n_client_errors']} error(s)"
                + (f", rollback {rollback_s}s after first high burn"
                   if rollback_s is not None else "")
                + ("" if row["zero_steady_recompiles"]
                   else "  [RECOMPILED]"),
                flush=True,
            )
            return row

        # Leg 1: healthy rollout mid-load.  Any mid-deploy output may
        # come from either generation, so either reference is valid.
        deploy_row = deploy_leg(
            "deploy", fleet.deploy_factory(ckpt_dir),
            (refs_seed, refs_trained),
        )

        # Leg 2: the SAME export again (gen2 weights == the now-serving
        # gen1, so every output stays checkable against the trained
        # refs) through a wedged factory — an honest canary-only TTFT
        # regression the burn watch must catch.
        base_factory = fleet.deploy_factory(ckpt_dir)

        def wedged_factory(role):
            remote = base_factory(role)
            orig = remote.submit_request

            def slow_submit(req):
                time.sleep(wedge_s)
                return orig(req)

            remote.submit_request = slow_submit
            return remote

        rollback_row = deploy_leg(
            "rollback", wedged_factory, (refs_trained,),
        )

        # Final timed pass on the post-rollback fleet: the promoted
        # generation, steady, zero recompiles — the ratchet number.
        before = live_compiles()
        client = run_open_loop(trace, url=url, collect_tokens=True)
        after = live_compiles()
        fresh = {
            n: after[n] - before[n] for n in after if n in before
        }
        final_row = {
            "tokens_per_sec": client["tokens_per_sec"],
            "makespan_s": client["makespan_s"],
            "n_errors": client["n_errors"],
            "byte_identical": all(
                r.get("output") == ref
                for r, ref in zip(client["per_request"], refs_trained)
            ),
            "worker_compiles_timed": fresh,
            "zero_recompiles": all(v == 0 for v in fresh.values()),
        }
        print(
            f"# serve deploy [    final]: "
            f"{final_row['tokens_per_sec']:,.1f} tokens/s on the "
            f"post-rollback fleet"
            + ("" if final_row["zero_recompiles"] else "  [RECOMPILED]"),
            flush=True,
        )

        result = {
            "deploy": deploy_row,
            "rollback": rollback_row,
            "final": final_row,
            "manifest_fingerprint": manifest.get("weights_fingerprint"),
            "fingerprint_match": bool(
                manifest.get("weights_fingerprint")
                and deploy_row["weights_fp"]
                == manifest["weights_fingerprint"]
            ),
            "rollback_within_window_s": cfg.window_s,
            "n_requests": n_requests,
            "n_tenants": n_tenants,
            "wedge_s": wedge_s,
            "seed": seed,
            "backend": jax.default_backend(),
        }
        zero_errors = (
            deploy_row["n_client_errors"] == 0
            and rollback_row["n_client_errors"] == 0
            and final_row["n_errors"] == 0
        )
        if deploy_row["state"] != "done":
            result["error"] = (
                f"healthy deploy ended {deploy_row['state']}, not done"
            )
        elif rollback_row["state"] != "rolled_back":
            result["error"] = (
                f"forced regression ended {rollback_row['state']}, "
                "not rolled_back"
            )
        elif not zero_errors:
            result["error"] = "client errors (dropped streams) observed"
        elif not (deploy_row["byte_identical"]
                  and rollback_row["byte_identical"]
                  and final_row["byte_identical"]):
            result["error"] = "fleet output diverged from generate()"
        elif not (deploy_row["zero_steady_recompiles"]
                  and rollback_row["zero_steady_recompiles"]
                  and final_row["zero_recompiles"]):
            result["error"] = (
                "steady-fleet compiles observed during a deploy"
            )
        elif rollback_row["rollback_s"] is None or (
                rollback_row["rollback_s"] > cfg.window_s):
            result["error"] = (
                f"rollback took {rollback_row['rollback_s']}s — "
                f"outside the {cfg.window_s}s burn window"
            )
        elif rollback_row["replicas_after"] != (
                rollback_row["replicas_before"]):
            result["error"] = (
                "rollback did not restore the pre-deploy replica set"
            )
        elif not result["fingerprint_match"]:
            result["error"] = (
                "served weights fingerprint != export manifest"
            )
    finally:
        try:
            router.close()
        finally:
            fleet.stop()
            shutil.rmtree(work_dir, ignore_errors=True)
    if out_path:
        with open(out_path, "w", encoding="utf-8") as fp:
            json.dump(result, fp, indent=1)
        print(f"# serve deploy artifact -> {out_path}", flush=True)
    return result


def bench_serve_chaos(n_requests=96, n_tenants=3, shared_frac=0.8,
                      mean_interarrival=0.04, shared_len=160,
                      page_size=16, max_batch=4, seed=0,
                      ttft_ms=400.0, tpot_ms=1000.0, slo_target=0.9,
                      pool_factor=3, slow_secs=15.0, out_path=None):
    """Serving chaos: the recorded 80%-shared-prefix trace, open-loop at
    saturating load through a 2-prefill + 2-decode router fleet, while
    1-of-4 replicas is KILLED and another SLOWED mid-run — with and
    without the mitigation stack (docs/serving.md "Surviving
    overload"):

    * **baseline**: the PR 13 router as-was — redistribute-on-death
      only; hedging off, breakers off, no autoscaler, no ladder.
    * **mitigated**: hedged prefills route around the slow replica,
      breakers fast-fail it, the SLO-burn autoscaler replaces the dead
      replica (and may add more / engage the degradation ladder when
      burn stays high).

    The committed artifact pins: mitigated TTFT attainment >= 2x the
    baseline under identical chaos, ZERO byte-identity regressions on
    surviving streams (a degraded stream must equal its un-degraded
    PREFIX — rungs only clamp budgets, never perturb bytes), zero
    post-warmup recompiles (compile_watch; the autoscaler's replicas
    share the compile cache), and every shed/failed request receiving
    a STRUCTURED error (JSON body over HTTP — status + cause +
    retry_after for sheds; never a hang, never a stdlib HTML page)."""
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.resilience import faults
    from ml_trainer_tpu.serving import (
        Autoscaler, AutoscalerConfig, Router, Server, SloPolicy,
    )
    from ml_trainer_tpu.serving.loadgen import (
        ScheduledRequest, run_open_loop, schedule_from_trace,
        schedule_to_records,
    )
    from ml_trainer_tpu.serving.slo import aggregate_timelines
    from ml_trainer_tpu.telemetry import compile_watch

    model = get_model("gpt2_tiny", max_len=256)
    variables = jax.jit(model.init, static_argnames="train")(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32),
        train=False,
    )
    rng = np.random.default_rng(seed)
    prefixes = [
        rng.integers(0, model.vocab_size, shared_len).astype(np.int32)
        for _ in range(n_tenants)
    ]
    arrivals = np.cumsum(rng.exponential(mean_interarrival, n_requests))
    trace = []
    for i in range(n_requests):
        t = int(rng.integers(0, n_tenants))
        if rng.random() < shared_frac:
            suffix = rng.integers(
                0, model.vocab_size, int(rng.integers(4, 17))
            ).astype(np.int32)
            prompt = np.concatenate([prefixes[t], suffix])
        else:
            prompt = rng.integers(
                0, model.vocab_size, int(rng.integers(16, 33))
            ).astype(np.int32)
        trace.append(ScheduledRequest(
            arrival_s=float(arrivals[i]), tenant=f"tenant{t}",
            prompt=prompt,
            max_new_tokens=int(rng.choice([8, 48], p=[0.6, 0.4])),
        ))
    schedule = schedule_from_trace(schedule_to_records(trace))
    policy = SloPolicy(ttft_ms=ttft_ms, tpot_ms=tpot_ms,
                       target=slo_target)
    pool_pages = pool_factor * max_batch * (256 // page_size) + 1
    server_kwargs = dict(
        max_batch=max_batch, kv_page_size=page_size,
        kv_pages=pool_pages, max_queue=2 * n_requests,
    )
    compile_watch.install()

    def build_router(mitigated: bool) -> Router:
        rk = {"slo": policy, "slo_timelines": 4 * n_requests}
        if mitigated:
            # Aggressive hedge clock: the chaos leg's whole point is
            # routing around a straggler fast.
            rk.update(hedge_quantile=0.9, hedge_factor=1.2,
                      hedge_min_s=0.05)
        else:
            rk.update(hedging=False, breaker_threshold=None)
        return Router.build(
            model, variables,
            roles=["prefill", "prefill", "decode", "decode"],
            router_kwargs=rk, **server_kwargs,
        )

    # Fleet indices are sorted-name order: decode0=0, decode1=1,
    # prefill0=2, prefill1=3.  Kill decode1, slow prefill0 — one dead,
    # one straggling, out of four.
    chaos_spec = (
        f"replica_kill@step=4,host=1;"
        f"replica_slow@step=1,host=2,secs={slow_secs}"
    )

    def warm_continuation_buckets():
        """Chaos shifts prefix-hit lengths (a redistribute-resume
        prefills prompt+committed tokens against a survivor's cache),
        so suffix buckets can appear mid-run that no replay pass
        visited.  Compile every plausible continuation bucket (8..128)
        up front — the compile cache is process-wide and keyed on the
        shared paged-model clone, so all legs (and the autoscaler's
        mid-run replicas) inherit them."""
        from ml_trainer_tpu.serving.engine import SlotDecodeEngine
        from ml_trainer_tpu.serving.scheduler import Request as _Req

        eng = SlotDecodeEngine(
            model, variables, max_batch=max_batch,
            kv_page_size=page_size, kv_pages=pool_pages,
        )
        wrng = np.random.default_rng(10_000 + seed)
        base = wrng.integers(0, model.vocab_size, 160).astype(np.int32)
        for k in (1, 1, 9, 17, 33, 65):  # first k=1 primes the trie
            prompt = np.concatenate([
                base, wrng.integers(0, model.vocab_size, k).astype(np.int32)
            ])
            req = _Req(prompt=prompt, max_new_tokens=2)
            if eng.admit(req, 0) == "active":
                while eng.active_count():
                    eng.step()

    warm_continuation_buckets()

    # Reference pass (no chaos): warms every compile (prefill buckets,
    # decode, kv export/import) AND records each request's un-degraded
    # output — the byte-identity anchor for the chaos legs.
    with build_router(mitigated=True) as router:
        host, port = router.serve_http(port=0)
        url = f"http://{host}:{port}"
        run_open_loop(schedule, url=url, time_scale=0.0)
        ref_run = run_open_loop(schedule, url=url, collect_tokens=True)
    refs = [r.get("output") for r in ref_run["per_request"]]
    if any(o is None for o in refs):
        raise RuntimeError(
            f"reference pass failed: {ref_run['n_errors']} error(s): "
            f"{ref_run['errors']}"
        )

    def run_leg(mitigated: bool) -> dict:
        router = build_router(mitigated)
        autoscaler = None
        if mitigated:
            autoscaler = Autoscaler(
                router,
                lambda role: Server(model, variables, role=role,
                                    **server_kwargs),
                AutoscalerConfig(
                    poll_interval_s=0.25, window_s=6.0,
                    min_window_requests=6, burn_high=1.5,
                    high_polls=2, cooldown_s=2.0, max_replicas=6,
                    min_prefill=2, min_decode=2, scale_down=False,
                ),
            ).start()
        err = None
        try:
            host, port = router.serve_http(port=0)
            url = f"http://{host}:{port}"
            # One untimed fault-free pass AT REAL TIME: prefix caches,
            # replica health and the hedging clock to steady state —
            # chaos hits a WARM fleet, and the hedge clock reflects
            # healthy first-result latency, not compressed-burst queues.
            run_open_loop(schedule, url=url)
            timed_t0 = time.monotonic()
            with faults.injected(chaos_spec):
                try:
                    with compile_watch.expect_no_compiles(
                        f"serve-chaos {'mitigated' if mitigated else 'baseline'}"
                    ):
                        client = run_open_loop(
                            schedule, url=url, collect_tokens=True,
                            timeout=180.0,
                        )
                except AssertionError as e:
                    err = str(e)
                    client = run_open_loop(
                        schedule, url=url, collect_tokens=True,
                        timeout=180.0,
                    )
            server_side = aggregate_timelines(
                router.slo.timelines(since=timed_t0), policy
            )
            snap = router.snapshot()
            asc_summary = (
                autoscaler.summary() if autoscaler is not None else None
            )
        finally:
            if autoscaler is not None:
                autoscaler.close()
            router.close()
        # Byte identity on surviving streams: a completed (possibly
        # budget-clamped) output must equal its un-degraded PREFIX.
        identity_bad = 0
        for row, ref in zip(client["per_request"], refs):
            out = row.get("output")
            if not row["ok"] or out is None:
                continue
            if len(out) > len(ref) or out != ref[: len(out)]:
                identity_bad += 1
        # Structured-failure audit: every failed row must carry a JSON
        # error body (status + cause), sheds a retry_after.
        failed = [r for r in client["per_request"] if not r["ok"]]
        unstructured = [
            r for r in failed
            if not (r.get("structured") or "retry after" in (r.get("error") or ""))
        ]
        leg = {
            "mitigated": mitigated,
            "tokens_per_sec": client["tokens_per_sec"],
            "makespan_s": client["makespan_s"],
            "n_completed": client["n_completed"],
            "n_errors": client["n_errors"],
            "n_shed": sum(
                1 for r in failed if r.get("retry_after") is not None
            ),
            "unstructured_failures": len(unstructured),
            "identity_regressions": identity_bad,
            "ttft_p50_ms": server_side["ttft_ms"]["p50"],
            "ttft_p99_ms": server_side["ttft_ms"]["p99"],
            "ttft_attainment": server_side["attainment"]["ttft"],
            "tpot_attainment": server_side["attainment"]["tpot"],
            "n_timelines": server_side["n_requests"],
            "migrations": snap["migrations_total"],
            "migrations_corrupt": snap["migrations_corrupt_total"],
            "redistributes": snap["redistributes_total"],
            "hedges": snap["hedges_total"],
            "hedge_wins": snap["hedge_wins_total"],
            "flaps_damped": snap["flaps_damped_total"],
            "shed_total": snap["shed_total"],
            "degradation": snap["degradation"],
            "zero_recompiles": err is None,
        }
        if err is not None:
            leg["recompile_error"] = err
        if asc_summary is not None:
            leg["autoscaler"] = asc_summary
        print(
            f"# serve chaos [{'mitigated' if mitigated else ' baseline'}]: "
            f"TTFT attainment {leg['ttft_attainment']:.3f} "
            f"(p99 {leg['ttft_p99_ms']} ms), "
            f"{leg['n_completed']}/{n_requests} completed, "
            f"{leg['hedges']} hedge(s), {leg['redistributes']} "
            f"redistribute(s), {leg['identity_regressions']} identity "
            f"regression(s)" + ("" if err is None else "  [RECOMPILED]"),
            flush=True,
        )
        return leg

    baseline = run_leg(mitigated=False)
    mitigated = run_leg(mitigated=True)
    ratio = round(
        mitigated["ttft_attainment"] / max(baseline["ttft_attainment"],
                                           0.01), 3
    )
    result = {
        "baseline": baseline,
        "mitigated": mitigated,
        "attainment_ratio": ratio,
        "attainment_win_2x": bool(ratio >= 2.0),
        "byte_identity_ok": (
            baseline["identity_regressions"] == 0
            and mitigated["identity_regressions"] == 0
        ),
        "zero_recompiles": bool(
            baseline["zero_recompiles"] and mitigated["zero_recompiles"]
        ),
        "all_failures_structured": (
            baseline["unstructured_failures"] == 0
            and mitigated["unstructured_failures"] == 0
        ),
        "chaos": chaos_spec,
        "slo": {"ttft_ms": ttft_ms, "tpot_ms": tpot_ms,
                "target": slo_target},
        "n_requests": n_requests,
        "n_tenants": n_tenants,
        "shared_frac": shared_frac,
        "shared_len": shared_len,
        "page_size": page_size,
        "max_batch": max_batch,
        "seed": seed,
        "backend": jax.default_backend(),
        # run_report-style summary: what acted, when, and what it cost.
        "run_report": {
            "fleet": "2 prefill + 2 decode (decode1 killed, "
                     "prefill0 slowed)",
            "mitigations": ["hedged prefills", "circuit breakers",
                            "SLO-burn autoscaler", "degradation ladder"],
            "autoscaler_actions": (
                mitigated.get("autoscaler") or {}
            ).get("counts", {}),
            "ladder_transitions": mitigated["degradation"]["transitions"],
            "attainment": {
                "baseline": baseline["ttft_attainment"],
                "mitigated": mitigated["ttft_attainment"],
                "ratio": ratio,
            },
        },
    }
    if not result["byte_identity_ok"]:
        result["error"] = "surviving streams diverged from reference"
    elif not result["zero_recompiles"]:
        result["error"] = "compiles observed during a chaos leg"
    elif not result["all_failures_structured"]:
        result["error"] = (
            f"unstructured failures: baseline "
            f"{baseline['unstructured_failures']}, mitigated "
            f"{mitigated['unstructured_failures']}"
        )
    elif not result["attainment_win_2x"]:
        result["error"] = (
            f"mitigated attainment only {ratio}x baseline (need >= 2x)"
        )
    if out_path:
        with open(out_path, "w", encoding="utf-8") as fp:
            json.dump(result, fp, indent=1)
        print(f"# serve chaos artifact -> {out_path}", flush=True)
    return result


def bench_spec(b=2, pattern_len=8, prompt_len=64, new_tokens=128,
               draft_k=8, reps=2, seed=0):
    """Speculative-decoding leg: tokens/s of the speculative loop
    (n-gram lookup drafter) vs the vanilla compiled decode loop, same
    model, same greedy workload.

    The workload is the one lookup drafting is FOR: a repetitive prompt
    (a short token pattern tiled to ``prompt_len``), greedy decoding.
    Greedy decode collapses into cycles quickly, and once a cycle is in
    the history the n-gram drafter predicts it almost perfectly —
    acceptance approaches K and each verify forward commits ~K+1
    tokens.  The model is ``gpt2_mini`` (≈29M params): big enough that
    a decode forward is weight-streaming-bound, so the K+1-token verify
    window costs ~2x a single-token step, not K+1x — the regime
    speculative decoding exists for (a gpt2_tiny-sized model is
    activation-bound and gains nothing).  Vanilla runs ONE compiled
    lax.scan (its best case: no per-token host dispatch at all), so the
    measured win is forwards saved, not dispatch saved.  Outputs are
    asserted byte-identical before timing — a speedup on wrong tokens
    is not a speedup.  Returns the JSON row (tokens/s both paths,
    speedup, acceptance histogram)."""
    from ml_trainer_tpu.generate import generate
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.speculative import speculative_generate

    model = get_model("gpt2_mini")
    variables = jax.jit(model.init, static_argnames="train")(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 8), jnp.int32),
        train=False,
    )
    rng = np.random.default_rng(seed)
    pattern = rng.integers(0, model.vocab_size, pattern_len)
    prompt = jnp.asarray(
        np.stack([
            np.tile(np.roll(pattern, i), prompt_len // pattern_len)
            for i in range(b)
        ]),
        jnp.int32,
    )

    ref = generate(model, variables, prompt, new_tokens)  # compile + warm
    out, stats = speculative_generate(
        model, variables, prompt, new_tokens, draft_k=draft_k,
        return_stats=True,
    )  # compile + warm
    identical = bool(np.array_equal(np.asarray(out), np.asarray(ref)))

    def timed(fn):
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            times.append(time.perf_counter() - t0)
        times.sort()
        return times[len(times) // 2]

    t_van = timed(lambda: generate(model, variables, prompt, new_tokens))
    t_spec = timed(lambda: speculative_generate(
        model, variables, prompt, new_tokens, draft_k=draft_k,
    ))
    total = b * new_tokens
    van_tps = total / t_van
    spec_tps = total / t_spec
    print(f"# spec vanilla: {van_tps:,.1f} tokens/s", flush=True)
    print(
        f"# spec speculative (K={draft_k}, ngram lookup): "
        f"{spec_tps:,.1f} tokens/s ({spec_tps / van_tps:.2f}x vanilla, "
        f"acceptance {stats['acceptance_rate']:.2f}, "
        f"{stats['tokens_per_step']:.2f} tokens/verify-step)", flush=True,
    )
    return {
        "model": "gpt2_mini",
        "batch": b,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "draft_k": draft_k,
        "drafter": "ngram",
        "greedy_identical": identical,
        "vanilla_tokens_per_sec": round(van_tps, 1),
        "spec_tokens_per_sec": round(spec_tps, 1),
        "speedup": round(spec_tps / van_tps, 2),
        "acceptance_rate": round(stats["acceptance_rate"], 4),
        "tokens_per_verify_step": round(stats["tokens_per_step"], 3),
        "accept_hist": stats["accept_hist"],
        "backend": jax.default_backend(),
    }


def bench_dispatch(iters=300):
    """pjit dispatch microbenchmark: per-call host overhead of the
    compiled train and decode steps, measured on programs whose
    EXECUTION is microseconds — so the wall clock per call is dominated
    by dispatch (argument flattening, executable lookup, transfer
    setup).  A compile-cache or dispatch-path regression moves these
    numbers far before it moves a real workload's throughput."""
    import statistics as _stats

    from ml_trainer_tpu.models import get_model

    model = get_model("gpt2_tiny", max_len=32, depth=1, embed_dim=32,
                      num_heads=2)
    x = jnp.zeros((1, 1), jnp.int32)
    variables = jax.jit(model.init, static_argnames="train")(
        {"params": jax.random.PRNGKey(0)}, x, train=False
    )
    params = variables["params"]

    # Decode-shaped step: one forward + argmax, state threaded.
    @jax.jit
    def decode_step(p, tok):
        logits = model.apply({"params": p}, tok, train=False)
        return jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)[:, None]

    # Train-shaped step: loss + grad + SGD update, donated params.
    def loss_fn(p, tok):
        logits = model.apply({"params": p}, tok, train=True)
        return jnp.mean(logits.astype(jnp.float32) ** 2)

    @jax.jit
    def train_step(p, tok):
        grads = jax.grad(loss_fn)(p, tok)
        return jax.tree.map(lambda a, g: a - 1e-3 * g, p, grads)

    def per_call(fn, *args):
        out = fn(*args)
        jax.block_until_ready(out)
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            times.append(time.perf_counter() - t0)
        return _stats.median(times) * 1e6  # µs

    decode_us = per_call(decode_step, params, x)
    train_us = per_call(train_step, params, x)
    print(f"# dispatch decode step: {decode_us:,.1f} µs/call", flush=True)
    print(f"# dispatch train step:  {train_us:,.1f} µs/call", flush=True)
    return {
        "decode_step_us_per_call": round(decode_us, 1),
        "train_step_us_per_call": round(train_us, 1),
        "iters": iters,
        "backend": jax.default_backend(),
    }


def _chip_peak_flops() -> float:
    """Peak bf16 FLOPs/s of one local chip — the MFU denominator.
    Owned by the telemetry spine now (telemetry/flops.py) so the bench,
    the MFU ledger, and live training telemetry can never disagree on
    the peak table; this alias keeps older callers working."""
    from ml_trainer_tpu.telemetry.flops import chip_peak_flops

    return chip_peak_flops()


def _compiled_flops(compiled) -> float | None:
    """FLOPs of ONE compiled train step via XLA cost analysis (measured on
    the actual executable, not an analytic formula).  None if unavailable."""
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0]
        flops = float(analysis.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:
        return None


# One row per model: (batch shape, task kind, constructor kwargs-builder).
# kwargs are built lazily (jnp.bfloat16 needs jax at call time, and keeping
# everything in one table means a new model cannot be half-registered).
EXTENDED_CONFIGS = {
    # The parity workload's model as a --one row (CPU-cheap): the
    # resilience acceptance gate compares `--one mlmodel` across commits
    # to prove the nonfinite guard adds no measurable step cost.
    "mlmodel": ((32, 32, 32, 3), "image", lambda: dict()),
    "resnet50": ((32, 224, 224, 3), "image", lambda: dict(dtype=jnp.bfloat16)),
    "vit_b16": ((32, 224, 224, 3), "image",
                lambda: dict(num_classes=1000, dtype=jnp.bfloat16)),
    "bert_base": ((32, 128), "tokens",
                  lambda: dict(num_classes=2, dtype=jnp.bfloat16)),
    # loss_chunk: bench the trainer's REAL GPT-2 path — the chunked
    # weight-tied LM loss that never materializes the [B, S, V] logits
    # (~0.8 GB at bs=8); the full-logits + criterion path is not how the
    # Trainer runs this model.
    "gpt2": ((8, 1024), "lm",
             lambda: dict(dtype=jnp.bfloat16, loss_chunk=128)),
}


def bench_one_model(name: str, batch_size: int | None = None) -> dict:
    """One north-star model: one full train step (bf16 compute, f32
    params), steady-state samples/sec + MFU (achieved FLOPs / chip peak).

    ``batch_size`` overrides the table's leading batch dim — the MFU
    ledger runs ResNet-50 at 32/128/256 to show where the MXU saturates.

    Everything device-touching is jitted: flax ``init`` executes EAGERLY
    by default — per-op dispatch, which over the remote TPU tunnel means
    one round trip per op and took ResNet-50's init past 45 minutes in
    round 3's first attempt.  ``jax.jit(model.init)`` makes it one
    compile + one execution."""
    import optax

    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.ops import get_criterion, get_optimizer
    from ml_trainer_tpu.train_state import TrainState

    def progress(msg):
        # One line per phase so a per-model TIMEOUT in bench_extended can
        # report WHERE the tunnel wedged (its error keeps the last line).
        print(f"# {name}: {msg}", file=sys.stderr, flush=True)

    bf16 = jnp.bfloat16
    shape, kind, make_kw = EXTENDED_CONFIGS[name]
    if batch_size is not None:
        shape = (batch_size,) + tuple(shape[1:])
    model = get_model(name, **make_kw())
    rng = np.random.default_rng(0)
    progress("transferring inputs to device")
    if kind == "image":
        x = jnp.asarray(rng.normal(size=shape), bf16)
        y = jnp.asarray(rng.integers(0, 10, shape[0]), jnp.int32)
    else:
        x = jnp.asarray(rng.integers(0, 1000, shape), jnp.int32)
        y = (
            jnp.roll(x, -1, axis=1)
            if kind == "lm"
            else jnp.asarray(rng.integers(0, 2, shape[0]), jnp.int32)
        )
    jax.block_until_ready((x, y))
    progress("inputs on device; compiling init")

    t_c = time.time()
    variables = jax.jit(model.init, static_argnames="train")(
        {"params": jax.random.PRNGKey(0)}, x, train=False
    )
    print(f"# {name}: init in {time.time() - t_c:.0f}s",
          file=sys.stderr, flush=True)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    tx = get_optimizer("adamw", 1e-4)
    criterion = get_criterion("cross_entropy")
    state = TrainState(
        step=jnp.zeros((), jnp.int32), params=params,
        opt_state=jax.jit(tx.init)(params), batch_stats=batch_stats,
        rng=jax.random.PRNGKey(1),
    )
    has_bs = bool(batch_stats)

    # Models carrying an active loss_chunk compute their own loss inside
    # the forward (chunked LM head) — same contract the Trainer uses.
    takes_targets = bool(getattr(model, "loss_chunk", 0))

    def step(state, x, y):
        def loss_fn(p):
            if takes_targets:
                loss = model.apply({"params": p}, x, train=True, targets=y)
                return loss, state.batch_stats
            if has_bs:
                out, mut = model.apply(
                    {"params": p, "batch_stats": state.batch_stats},
                    x, train=True, mutable=["batch_stats"],
                )
                return criterion(out, y), mut["batch_stats"]
            out = model.apply({"params": p}, x, train=True)
            return criterion(out, y), state.batch_stats

        (loss, new_bs), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        updates, opt_state = tx.update(
            grads, state.opt_state, state.params
        )
        return (
            state.replace(
                step=state.step + 1,
                params=optax.apply_updates(state.params, updates),
                opt_state=opt_state,
                batch_stats=new_bs,
            ),
            loss,
        )

    # Compile ONCE; the same executable feeds the FLOPs analysis and the
    # timing loop (a second jit-path compile would double the
    # remote-compile tunnel cost).  The state is donated: the timing loop
    # rebinds it every call, and without donation every step allocates a
    # second copy of params+moments before freeing the old one.
    step = jax.jit(step, donate_argnums=0)
    t_c = time.time()
    compiled = step.lower(state, x, y).compile()
    print(f"# {name}: compiled in {time.time() - t_c:.0f}s",
          file=sys.stderr, flush=True)
    # FLOPs: XLA's measured cost analysis when the executable exposes
    # it, else the telemetry spine's analytic accounting — the SAME
    # accounting the trainer's live MFU line uses (telemetry/flops.py).
    flops = _compiled_flops(compiled)
    flops_source = "xla"
    if flops is None:
        from ml_trainer_tpu.telemetry.flops import train_step_flops

        flops = train_step_flops(model, shape)
        flops_source = "analytic"
    rate, state = _steady_state_rate(
        compiled, state, [(x, y)], warmup=3, iters=20
    )
    # Step-time distribution: a short FENCED per-step pass (StepTimer
    # record_steps) — the mean above keeps dispatch pipelining live, the
    # percentiles pay one fence per step for an honest tail.
    ptimer = StepTimer(warmup=2, record_steps=True)
    for _ in range(12):
        state, loss = compiled(state, x, y)
        ptimer.tick(loss, 1)
    p50, p99 = ptimer.p50(), ptimer.p99()
    # MFU only means something against the real chip's peak.
    on_tpu = jax.default_backend() == "tpu"
    mfu = rate * flops / _chip_peak_flops() if (flops and on_tpu) else None
    # HBM columns (telemetry/memory.py): the LIVE per-device peak (TPU
    # allocator stats; live-array accounting on CPU, which cannot see
    # XLA's scratch arena) beside the ANALYTIC ledger's peak prediction.
    from ml_trainer_tpu.telemetry import memory as _memory

    mem_live = _memory.live_memory_snapshot()
    mem_ledger = _memory.bench_step_ledger(state, model, (x, y))
    return {
        "model": name, "batch_shape": list(shape),
        "samples_per_sec": round(rate * shape[0], 1),
        "step_ms_p50": round(p50 * 1e3, 3) if p50 is not None else None,
        "step_ms_p99": round(p99 * 1e3, 3) if p99 is not None else None,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "flops_per_step": flops,
        "flops_source": flops_source if flops else None,
        "peak_hbm_bytes": int(mem_live["max_peak_bytes_in_use"]),
        "peak_hbm_source": mem_live["source"],
        "analytic_hbm_bytes": int(mem_ledger.peak_bytes()),
        "analytic_hbm_resident_bytes": int(mem_ledger.resident_bytes()),
        # mfu can be null on a healthy TPU run (cost analysis unavailable),
        # so the row records the backend explicitly — recovery's done-check
        # must not confuse a CPU-fallback row with a TPU measurement.
        "backend": jax.default_backend(),
    }


def bench_chaos(size=2048, batch_size=32, save_every=8, preempt_step=41,
                epochs=1):
    """Chaos leg: the measurable cost of resilience (CPU-safe, tiny model).

    Three numbers a preemptible-fleet operator budgets around:

    * ``ckpt_overhead_pct`` — wall-clock overhead of step-granular
      checkpoints (``save_every_steps``) vs the same epoch without them
      (the async writer should hide most of the I/O);
    * ``steps_lost_on_preempt`` — training steps between the last
      committed step checkpoint and the preemption point (bounded by
      ``save_every_steps - 1``);
    * ``time_to_recover_secs`` — wall clock for ``fit(resume=True)`` to
      restore the emergency checkpoint and finish the interrupted epoch.
    """
    import os
    import shutil
    import tempfile

    from ml_trainer_tpu import Trainer, MLModel
    from ml_trainer_tpu.data import SyntheticCIFAR10
    from ml_trainer_tpu.resilience import faults
    from ml_trainer_tpu import checkpoint as ckpt

    def fresh(model_dir, **kw):
        return Trainer(
            MLModel(),
            datasets=(SyntheticCIFAR10(size=size, seed=0),
                      SyntheticCIFAR10(size=256, seed=1)),
            epochs=epochs, batch_size=batch_size, model_dir=model_dir,
            metric=None, lr=0.01, **kw,
        )

    dirs = [tempfile.mkdtemp(prefix="bench_chaos_") for _ in range(4)]
    try:
        # Warmup run: pays one-time costs (first-touch numpy/XLA paths)
        # so the base-vs-checkpointed comparison is order-independent.
        fresh(dirs[3]).fit()
        # 1. checkpoint-save overhead: same epoch with/without step saves.
        t0 = time.perf_counter()
        fresh(dirs[0]).fit()
        base_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        fresh(dirs[1], save_every_steps=save_every).fit()
        ckpt.wait_for_checkpoints()
        ckpt_s = time.perf_counter() - t0
        overhead_pct = (ckpt_s / base_s - 1.0) * 100.0
        print(f"# chaos ckpt overhead: {base_s:.2f}s -> {ckpt_s:.2f}s "
              f"({overhead_pct:+.1f}% with save_every_steps={save_every})",
              flush=True)

        # 2. preemption: inject at a step between two step-checkpoints.
        with faults.injected(f"preempt@step={preempt_step}"):
            t = fresh(dirs[2], save_every_steps=save_every)
            t.fit()
        assert t.preempted, "preempt fault did not fire"
        latest = ckpt.latest_valid_checkpoint(
            os.path.join(dirs[2], "checkpoints")
        )
        _, hist, _ = ckpt.restore_checkpoint(
            latest, ckpt.fetch_to_host(t.state)
        )
        saved_step = hist.get("mid_epoch", {}).get("batches_done", 0)
        # The emergency save checkpoints the preemption step itself, so
        # steps re-trained on resume measure the NO-emergency floor: the
        # cadence gap a hard-kill (no clean exit) would lose.
        cadence_lost = preempt_step - (
            preempt_step // save_every
        ) * save_every
        print(f"# chaos preempt at step {preempt_step}: emergency save at "
              f"batch {saved_step}, steps lost 0 (clean exit) / "
              f"{cadence_lost} (hard kill, cadence {save_every})",
              flush=True)

        # 3. time-to-recover: resume and finish the interrupted epoch.
        t0 = time.perf_counter()
        r = fresh(dirs[2], save_every_steps=save_every)
        r.fit(resume=True)
        recover_s = time.perf_counter() - t0
        print(f"# chaos time-to-recover: {recover_s:.2f}s "
              f"(restore + {size // batch_size - saved_step} remaining "
              "step(s) + validation)", flush=True)
        return {
            "ckpt_overhead_pct": round(overhead_pct, 1),
            "base_epoch_secs": round(base_s, 2),
            "ckpt_epoch_secs": round(ckpt_s, 2),
            "save_every_steps": save_every,
            "preempt_step": preempt_step,
            "emergency_saved_at_batch": saved_step,
            "steps_lost_clean_exit": 0,
            "steps_lost_hard_kill": cadence_lost,
            "time_to_recover_secs": round(recover_s, 2),
            "resumed_epochs": r.history["epochs"],
            "backend": jax.default_backend(),
        }
    finally:
        for d in dirs:
            shutil.rmtree(d, ignore_errors=True)


def bench_elastic():
    """Elastic chaos leg: drive ``scripts/elastic_smoke.py`` (its phases
    need their own processes for per-phase virtual device counts) and
    distill the numbers a preemptible-fleet operator budgets around:

    * the in-process drain→reshape→continue downtime and steps-lost
      (clean-drain path: a ``host_kill`` fault drops 1 of 2 simulated
      hosts, 8 -> 4 devices, same ``fit()`` call finishes with the
      uninterrupted trajectory);
    * the hard-kill restart path: a real 2-process cluster loses a host
      to ``os._exit`` with NO emergency checkpoint, and the restart at
      a different topology is bounded by the ``save_every_steps``
      cadence — ``time_to_recover_secs`` is its wall-clock.

    The committed ``docs/elastic_chaos_cpu.json`` pins these; the
    fastlane gate (``scripts/bench_gate.py gate_elastic``) hard-fails
    the invariants and ratchets the recovery rate.
    """
    import os
    import subprocess
    import sys

    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts",
        "elastic_smoke.py",
    )
    proc = subprocess.run(
        [sys.executable, script], capture_output=True, text=True,
        timeout=500, env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    line = next(
        (ln for ln in proc.stdout.splitlines()
         if ln.startswith("ELASTIC_SMOKE_RESULT ")), None,
    )
    if proc.returncode != 0 or line is None:
        tail = (proc.stdout + proc.stderr).strip().splitlines()[-8:]
        return {"ok": False, "error": " | ".join(tail)}
    result = json.loads(line[len("ELASTIC_SMOKE_RESULT "):])
    ip, rs = result["in_process"], result.get("restart", {})
    out = {
        "ok": result["ok"],
        "reshape_downtime_secs": ip["reshape_downtime_secs"],
        "steps_lost_clean_drain": ip["steps_lost"],
        "trajectory_equal": ip["trajectory_equal"],
        "bit_exact_resumable": ip["bit_exact_resumable"],
        "old_topology": ip["old_topology"],
        "new_topology": ip["new_topology"],
        "backend": jax.default_backend(),
    }
    if rs:
        out.update(
            steps_lost_hard_kill=rs["steps_lost"],
            steps_lost_bound=rs["steps_lost_bound"],
            time_to_recover_secs=rs["time_to_recover_secs"],
        )
    print(
        f"# elastic: reshape {out['old_topology']} -> "
        f"{out['new_topology']} in {out['reshape_downtime_secs']}s, "
        f"hard-kill restart lost {out.get('steps_lost_hard_kill', '?')} "
        f"step(s), recovered in "
        f"{out.get('time_to_recover_secs', '?')}s", flush=True,
    )
    return out


def bench_telemetry(batch_size=32, reps=3, warmup=5, iters=40):
    """Telemetry-overhead leg: the instrumented train step (on-device
    grad/param/update-norm stats, Trainer(telemetry=True)) vs the bare
    step, same model, same pre-materialized device batches.

    The claim under test (docs/observability.md): step telemetry rides
    INSIDE the one compiled program — no extra dispatches, no host
    syncs — so its cost is a few reductions, targeted at <2% step time
    even on the dispatch-bound CPU LeNet row (on a real chip the norms
    vanish into the step).  Interleaves ``reps`` measurement passes of
    each variant and takes each side's best rate, the standard
    noise-floor trick for single-digit-percent comparisons."""
    from ml_trainer_tpu import Trainer, MLModel
    from ml_trainer_tpu.data import SyntheticCIFAR10, prefetch_to_device
    from ml_trainer_tpu.utils.functions import custom_pre_process_function

    def make(telemetry):
        ds = SyntheticCIFAR10(
            size=PARITY_DS_SIZE, transform=custom_pre_process_function()
        )
        return Trainer(
            MLModel(), datasets=(ds, ds), epochs=1, batch_size=batch_size,
            model_dir="/tmp/bench_telemetry", metric="accuracy", lr=0.01,
            telemetry=telemetry,
        )

    def batches_for(trainer):
        return [
            (x, y, jnp.asarray(1.0, jnp.float32))
            for _, (x, y) in zip(
                range(16),
                prefetch_to_device(
                    trainer.train_loader, size=2,
                    sharding=trainer._batch_sharding,
                ),
            )
        ]

    bare = make(False)
    instr = make(True)
    bare_batches = batches_for(bare)
    instr_batches = batches_for(instr)
    best = {"bare": 0.0, "telemetry": 0.0}
    state_bare, state_instr = bare.state, instr.state
    for _ in range(reps):
        r, state_bare = _steady_state_rate(
            bare._train_step, state_bare, bare_batches,
            warmup=warmup, iters=iters,
        )
        best["bare"] = max(best["bare"], r)
        r, state_instr = _steady_state_rate(
            instr._train_step, state_instr, instr_batches,
            warmup=warmup, iters=iters,
        )
        best["telemetry"] = max(best["telemetry"], r)
    bare_sps = best["bare"] * batch_size
    instr_sps = best["telemetry"] * batch_size
    overhead_pct = (bare_sps / instr_sps - 1.0) * 100.0
    # Proof of the no-extra-programs claim, in the artifact itself.
    compiles = {
        "bare": bare._train_step._cache_size(),
        "telemetry": instr._train_step._cache_size(),
    }
    print(f"# telemetry bare:         {bare_sps:,.1f} samples/s", flush=True)
    print(f"# telemetry instrumented: {instr_sps:,.1f} samples/s "
          f"({overhead_pct:+.2f}% step-time overhead, "
          f"{compiles['telemetry']} compiled program(s))", flush=True)
    return {
        "model": "mlmodel",
        "batch_size": batch_size,
        "bare_samples_per_sec": round(bare_sps, 1),
        "telemetry_samples_per_sec": round(instr_sps, 1),
        "overhead_pct": round(overhead_pct, 2),
        "target_overhead_pct": 2.0,
        "compiled_programs": compiles,
        "backend": jax.default_backend(),
    }


def bench_mixed(n_devices=8, batch_size=16, seq_len=32, iters=8, warmup=2,
                reps=2, out_path=None):
    """Mixed-precision / sharded-update matrix on a virtual pure-DP mesh
    (the ``dryrun_multichip`` style: CPU with forced host devices, same
    compiled collectives as the chip):

        {fp32, bf16} x {fused-psum, bucketed reduce-scatter + sharded
        update}

    Each row is the REAL ``Trainer`` train step (the exact code path of
    training runs) on pre-materialized device batches: steady-state step
    time, per-op comm bytes (analytic, trace-time), the per-bucket
    reduce-scatter/all-gather breakdown for the sharded rows, and the
    compiled-program-count pin.  Needs ``n_devices`` local devices; when
    fewer exist the measurement respawns itself in a subprocess with
    ``--xla_force_host_platform_device_count`` (the backend's device
    count is fixed at init)."""
    import os
    import subprocess

    if len(jax.devices()) < n_devices:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
        env["ML_TRAINER_TPU_MIXED_CHILD"] = "1"
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--mixed",
             "--mixed-devices", str(n_devices)],
            env=env, capture_output=True, text=True, timeout=1500,
        )
        result = None
        for line in r.stdout.splitlines():
            print(line, flush=True)  # re-surface the child's rows
            if line.startswith("{"):
                try:
                    result = json.loads(line).get("mixed")
                except ValueError:
                    pass
        if r.returncode != 0 or result is None:
            tail = (r.stderr or "").strip().splitlines()
            return {"error": f"mixed worker failed (rc={r.returncode}): "
                             f"{tail[-1] if tail else 'no stderr'}"}
        if out_path:
            _write_mixed_artifact(result, out_path)
        return result

    from ml_trainer_tpu import Trainer
    from ml_trainer_tpu.data import SyntheticTokens, prefetch_to_device
    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.parallel.comm_stats import (
        comm_bucket_bytes,
        comm_bytes,
        reset_comm_stats,
    )

    ds = SyntheticTokens(
        size=max(batch_size * 8, 64), seq_len=seq_len, vocab_size=256,
        seed=0,
    )
    rows = []
    for precision in ("fp32", "bf16"):
        for dp_update in ("fused", "sharded"):
            reset_comm_stats()
            trainer = Trainer(
                get_model("gpt2_tiny", vocab_size=256),
                datasets=(ds, ds), epochs=1, batch_size=batch_size,
                model_dir=f"/tmp/bench_mixed_{precision}_{dp_update}",
                mesh_shape={"data": n_devices}, optimizer="adamw",
                metric=None, lr=1e-3, precision=precision,
                dp_update=dp_update, bucket_mb=0.25,
            )
            batches = [
                (x, y, jnp.asarray(1.0, jnp.float32))
                for _, (x, y) in zip(
                    range(4),
                    prefetch_to_device(
                        trainer.train_loader, size=2,
                        sharding=trainer._batch_sharding,
                    ),
                )
            ]
            # One probed step first: finite-loss evidence (the state it
            # returns replaces the donated input).
            state, loss, *_ = trainer._train_step(
                trainer.state, *batches[0]
            )
            loss = float(loss)
            best = 0.0
            for _ in range(reps):
                r, state = _steady_state_rate(
                    trainer._train_step, state, batches,
                    warmup=warmup, iters=iters,
                )
                best = max(best, r)
            comm = {k: round(v, 1) for k, v in comm_bytes().items()}
            buckets = {
                op: {b: round(v, 1) for b, v in bs.items()}
                for op, bs in comm_bucket_bytes().items()
            }
            row = {
                "precision": precision,
                "dp_update": dp_update,
                "samples_per_sec": round(best * batch_size, 1),
                "step_ms": round(1e3 / best, 3) if best else None,
                "loss": round(loss, 4),
                "loss_finite": bool(np.isfinite(loss)),
                "comm_bytes": comm,
                "comm_buckets": buckets,
                "compiled_programs_constant":
                    trainer._train_step._cache_size() == 1,
            }
            if dp_update == "sharded":
                row["n_buckets"] = len(trainer._bucket_plan.buckets)
                row["overlap_fraction"] = round(
                    trainer._bucket_plan.overlap_fraction, 4
                )
            rows.append(row)
            print(
                f"# mixed {precision:>4}/{dp_update:<7} "
                f"{row['samples_per_sec']:>8,.1f} samples/s  "
                f"step {row['step_ms']:.2f} ms  loss {loss:.4f}  "
                f"comm {sum(comm.values()):,.0f} B/step", flush=True,
            )

    def rate(precision, dp_update):
        for row in rows:
            if (row["precision"], row["dp_update"]) == (precision, dp_update):
                return row["samples_per_sec"]
        return 0.0

    result = {
        "model": "gpt2_tiny(vocab=256)",
        "n_devices": n_devices,
        "batch_size": batch_size,
        "seq_len": seq_len,
        "backend": jax.default_backend(),
        "rows": rows,
        # Headline ratios: the sharded-update win at each precision, and
        # the full-stack bf16+sharded vs the fp32 fused baseline.
        "sharded_vs_fused_fp32": round(
            rate("fp32", "sharded") / max(rate("fp32", "fused"), 1e-9), 3
        ),
        "sharded_vs_fused_bf16": round(
            rate("bf16", "sharded") / max(rate("bf16", "fused"), 1e-9), 3
        ),
        "bf16_sharded_vs_fp32_fused": round(
            rate("bf16", "sharded") / max(rate("fp32", "fused"), 1e-9), 3
        ),
    }
    if out_path:
        _write_mixed_artifact(result, out_path)
    return result


def _write_mixed_artifact(result, out_path) -> None:
    import os

    payload = dict(result)
    payload["generated_by"] = "bench.py --mixed"
    payload["date"] = _utcnow()
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    tmp = out_path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fp:
        json.dump(payload, fp, indent=1)
    os.replace(tmp, out_path)
    print(f"# mixed artifact -> {out_path}", flush=True)


PIPELINE_MATRIX = (
    # (n_stage_devices, n_virtual, n_micro, schedule, remat)
    (2, 1, 4, "gpipe", False),
    (2, 1, 4, "1f1b", False),
    (4, 1, 8, "gpipe", False),       # the S=4/M=8 acceptance pair
    (4, 1, 8, "1f1b", False),
    (4, 1, 8, "zb", False),
    (4, 2, 8, "interleaved", False),  # 8 virtual stages on 4 devices
    (4, 1, 8, "gpipe", True),        # memory-bounded pair: gpipe remat
    (4, 1, 8, "1f1b", True),         # vs 1F1B's O(S) combined backward
)


def bench_pipeline(n_devices=4, width=64, mb_rows=8, iters=20, warmup=5,
                   reps=2, out_path=None):
    """Pipeline-schedule matrix on a virtual ``stage`` mesh (the
    ``dryrun_multichip`` style: CPU with forced host devices, same
    compiled collectives as the chip): one jitted ``value_and_grad`` of
    a pipelined stage stack per row — the schedule engine itself, no
    trainer machinery in the timed region.

    Each row records the fenced steady-state step time, the analytic
    tick-table facts (bubble fraction, executed-compute waste, stash
    sizing from ``pipeline_schedule_info``), the per-hop comm bytes
    (``comm_bytes_by_hop{schedule=,hop=}``), a trajectory-equality check
    against the serial fold (value AND grad), and the compiled-program
    pin.  Headline: the 1F1B-vs-GPipe step-time ratio at S=4/M=8 —
    GPipe's scan executes garbage compute in its bubble slots on every
    device while the tick-table engine skips idle slots, so 1F1B should
    hold or beat it.  Needs ``n_devices`` local devices; with fewer the
    measurement respawns itself in a subprocess with
    ``--xla_force_host_platform_device_count``."""
    import os
    import subprocess

    if len(jax.devices()) < n_devices:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
        env["ML_TRAINER_TPU_PIPELINE_CHILD"] = "1"
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--pipeline",
             "--pipeline-devices", str(n_devices)],
            env=env, capture_output=True, text=True, timeout=1500,
        )
        result = None
        for line in r.stdout.splitlines():
            print(line, flush=True)  # re-surface the child's rows
            if line.startswith("{"):
                try:
                    result = json.loads(line).get("pipeline")
                except ValueError:
                    pass
        if r.returncode != 0 or result is None:
            tail = (r.stderr or "").strip().splitlines()
            return {"error": f"pipeline worker failed (rc={r.returncode}): "
                             f"{tail[-1] if tail else 'no stderr'}"}
        if out_path:
            _write_pipeline_artifact(result, out_path)
        return result

    import numpy as _np

    from ml_trainer_tpu.parallel import create_mesh
    from ml_trainer_tpu.parallel.comm_stats import (
        comm_hop_bytes,
        reset_comm_stats,
    )
    from ml_trainer_tpu.parallel.pipeline import (
        pipeline_apply,
        pipeline_schedule_info,
        reset_pipeline_info,
        stack_stage_params,
    )

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"] + p["b"])

    def make_stack(n, seed):
        rng = _np.random.default_rng(seed)
        return stack_stage_params([
            {"w": jnp.asarray(rng.normal(0, 0.5, (width, width)),
                              jnp.float32),
             "b": jnp.asarray(rng.normal(0, 0.1, (width,)), jnp.float32)}
            for _ in range(n)
        ])

    rows = []
    for S, V, M, schedule, remat in PIPELINE_MATRIX:
        if S > n_devices:
            continue
        G = S * V
        mesh = create_mesh({"stage": S}, devices=jax.devices()[:S])
        stacked = make_stack(G, seed=G + M)
        x = jnp.asarray(
            _np.random.default_rng(M + S).normal(size=(M * mb_rows, width)),
            jnp.float32,
        )
        reset_comm_stats()
        reset_pipeline_info()

        @jax.jit
        def vag(p, x=x, mesh=mesh, M=M, schedule=schedule, V=V,
                remat=remat):
            return jax.value_and_grad(lambda pp: jnp.sum(pipeline_apply(
                stage_fn, pp, x, mesh, n_microbatches=M,
                schedule=schedule, n_virtual=V, remat=remat) ** 2))(p)

        v, g = jax.block_until_ready(vag(stacked))
        # Trajectory equality vs the serial fold (value AND grad).
        def serial_loss(p):
            def body(carry, pv):
                return stage_fn(pv, carry), None
            out, _ = jax.lax.scan(body, x, p)
            return jnp.sum(out ** 2)

        vs, gs = jax.value_and_grad(serial_loss)(stacked)
        equal = bool(_np.isclose(float(v), float(vs), rtol=1e-5)) and all(
            _np.allclose(_np.asarray(a), _np.asarray(b), atol=2e-4,
                         rtol=1e-4)
            for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(gs))
        )
        best = None
        for _ in range(reps):
            for _ in range(warmup):
                jax.block_until_ready(vag(stacked))
            t0 = time.perf_counter()
            for _ in range(iters):
                jax.block_until_ready(vag(stacked))
            dt = (time.perf_counter() - t0) / iters
            best = dt if best is None else min(best, dt)
        info = pipeline_schedule_info().get(schedule, {})
        hops = {
            h: round(v_, 1)
            for h, v_ in comm_hop_bytes().get(schedule, {}).items()
        }
        row = {
            "schedule": schedule, "n_stage_devices": S, "n_virtual": V,
            "n_stages": G, "n_micro": M, "remat": remat,
            "step_ms": round(best * 1e3, 3),
            "serial_equal": equal,
            "compiled_programs_constant": vag._cache_size() == 1,
            "bubble_fraction": info.get("bubble_fraction"),
            "wasted_compute_fraction": info.get("wasted_compute_fraction"),
            "stash_slots": info.get("stash_slots"),
            "comm_bytes_by_hop": hops,
        }
        rows.append(row)
        print(
            f"# pipeline S={S} V={V} M={M} {schedule:>11}/"
            f"{'remat' if remat else 'store'} {row['step_ms']:>8.3f} ms  "
            f"bubble {row['bubble_fraction']}  "
            f"equal={'Y' if equal else 'N'}", flush=True,
        )

    def step_ms(schedule, S, M, remat=False):
        for row in rows:
            if (row["schedule"], row["n_stage_devices"], row["n_micro"],
                    row["remat"]) == (schedule, S, M, remat):
                return row["step_ms"]
        return None

    g48, f48 = step_ms("gpipe", 4, 8), step_ms("1f1b", 4, 8)
    result = {
        "kind": "pipeline schedule x stages matrix (value_and_grad of a "
                f"{width}-wide tanh stage stack, {mb_rows}-row "
                "microbatches)",
        "n_devices": n_devices,
        "backend": jax.default_backend(),
        "rows": rows,
        # Headline: >1.0 means 1F1B beats GPipe at the acceptance config.
        "gpipe_over_1f1b_s4_m8": (
            round(g48 / f48, 3) if g48 and f48 else None
        ),
        "gpipe_over_1f1b_s4_m8_remat": (
            round((step_ms("gpipe", 4, 8, True) or 0)
                  / step_ms("1f1b", 4, 8, True), 3)
            if step_ms("1f1b", 4, 8, True) else None
        ),
    }
    if out_path:
        _write_pipeline_artifact(result, out_path)
    return result


def _write_pipeline_artifact(result, out_path) -> None:
    import os

    payload = dict(result)
    payload["generated_by"] = "bench.py --pipeline"
    payload["date"] = _utcnow()
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    tmp = out_path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fp:
        json.dump(payload, fp, indent=1)
    os.replace(tmp, out_path)
    print(f"# pipeline artifact -> {out_path}", flush=True)


def bench_kernels(iters=40, warmup=5, reps=2, new_tokens=12,
                  out_path=None):
    """Kernel-layer microbench + decode-path gate evidence for the
    ``ops/kernels/`` Pallas pass (paged-attention decode, fused
    sharded-Adam tail, int8 weight-quantized matmul).

    Each kernel row times its lax reference against the dispatcher's
    ``implementation='auto'`` path on THIS backend.  Off-TPU 'auto'
    resolves to the reference, so the before/after pair converges by
    construction — that is the honest CPU artifact: parity (interpret
    mode, bit-for-bit) and engine byte-identity are the gate, the
    timing columns ratchet the shared program, and the TPU win shows up
    only when the same artifact is regenerated on a chip.  The decode
    leg runs the REAL engine twice (gather+flash vs ``paged_kernel``):
    byte-identical outputs across ragged traffic, steady-state compiled
    decode step time, and the zero-post-warmup-recompile pin."""
    import functools

    import optax

    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.ops.kernels import (
        adam_scalars,
        fused_adam_update,
        int8_matmul,
        paged_attention,
        paged_attention_reference,
        quantize_per_channel,
        unscale_sqsum,
    )
    from ml_trainer_tpu.serving import Server
    from ml_trainer_tpu.serving.engine import SlotDecodeEngine
    from ml_trainer_tpu.telemetry import compile_watch

    backend = jax.default_backend()

    def best_us(fn, *args):
        f = jax.jit(fn)
        jax.block_until_ready(f(*args))  # compile outside the timer
        best = float("inf")
        for _ in range(reps):
            for _ in range(warmup):
                out = f(*args)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(iters):
                out = f(*args)
            jax.block_until_ready(out)
            best = min(best, (time.perf_counter() - t0) / iters)
        return round(best * 1e6, 2)

    def bits_equal(a, b):
        return bool(all(
            np.array_equal(np.asarray(x), np.asarray(y))
            for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
        ))

    kernels = {}

    # ---- (a) paged-attention decode: gather+attention vs fused kernel.
    b, h, d, P, ps, n_pages = 4, 4, 32, 4, 16, 32
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (b, h, d), jnp.float32)
    k_pool = jax.random.normal(kk, (n_pages, h, ps, d), jnp.float32)
    v_pool = jax.random.normal(kv, (n_pages, h, ps, d), jnp.float32)
    table = jax.random.randint(
        jax.random.PRNGKey(7), (b, P), 1, n_pages, jnp.int32
    )
    # Full row, 1-token row, mid-page partial, partial last page.
    lengths = jnp.asarray([P * ps, 1, 17, 40], jnp.int32)
    ref_us = best_us(
        functools.partial(paged_attention, implementation="reference"),
        q, k_pool, v_pool, table, lengths,
    )
    auto_us = best_us(paged_attention, q, k_pool, v_pool, table, lengths)
    parity = bits_equal(
        paged_attention(q, k_pool, v_pool, table, lengths,
                        implementation="pallas", interpret=True),
        paged_attention_reference(q, k_pool, v_pool, table, lengths),
    )
    # Gather-overhead diagnostic: the same attention on PRE-gathered
    # contiguous KV — the delta vs the reference is the per-step copy
    # the fused kernel eliminates on TPU.
    kc = k_pool[table].transpose(0, 2, 1, 3, 4).reshape(b, h, P * ps, d)
    vc = v_pool[table].transpose(0, 2, 1, 3, 4).reshape(b, h, P * ps, d)
    valid = (
        jnp.arange(P * ps)[None, :] < lengths[:, None]
    )[:, None, None, :]

    from ml_trainer_tpu.ops.attention import dot_product_attention

    def contiguous_attn(qv, kx, vx, mask):
        out = dot_product_attention(qv[:, :, None, :], kx, vx, mask=mask)
        return out[:, :, 0, :]

    contig_us = best_us(contiguous_attn, q, kc, vc, valid)
    kernels["paged_attention"] = {
        "shape": {"batch": b, "heads": h, "head_dim": d,
                  "pages_per_seq": P, "page_size": ps},
        "reference_us": ref_us,
        "kernel_us": auto_us,
        "speedup": round(ref_us / max(auto_us, 1e-9), 3),
        "interpret_parity": parity,
        "contiguous_attn_us": contig_us,
        "gather_overhead_fraction": round(
            max(0.0, 1.0 - contig_us / max(ref_us, 1e-9)), 3
        ),
    }

    # ---- (b) fused unscale+clip+Adam tail over a sharded leaf set.
    keys = jax.random.split(jax.random.PRNGKey(1), 8)
    shapes = {"wte": (1024, 64), "w1": (64, 256), "b1": (256,),
              "w2": (256, 64), "b2": (64,), "ln": (64,)}
    params = {
        n: jax.random.normal(k, s, jnp.float32) * 0.02
        for (n, s), k in zip(shapes.items(), keys)
    }
    grads = {
        n: jax.random.normal(jax.random.fold_in(keys[-1], i), s,
                             jnp.float32)
        for i, (n, s) in enumerate(shapes.items())
    }
    lr, clip, denom = 1e-3, 1.0, 8.0

    def sched(_count):
        return jnp.asarray(lr, jnp.float32)

    tx = optax.chain(optax.identity(), optax.adam(sched))
    opt_state = tx.init(params)
    one = jnp.asarray(1.0, jnp.float32)

    def ref_tail(g, p, st):
        g = jax.tree.map(lambda t: t / denom, g)
        sq = sum(
            jnp.sum(jnp.square(t.astype(jnp.float32)))
            for t in jax.tree.leaves(g)
        )
        factor = clip / jnp.maximum(jnp.sqrt(sq), clip)
        g = jax.tree.map(lambda t: t * factor, g)
        updates, new_st = tx.update(g, st, p)
        updates = jax.tree.map(lambda u: u * one, updates)
        return optax.apply_updates(p, updates), new_st

    def fused_tail(g, p, st):
        _e, (adam_st, sched_st) = st
        g_def = jax.tree.structure(g)
        gs, sq = [], 0.0
        for t in jax.tree.leaves(g):
            th, s = unscale_sqsum(t, denom, compute_sq=True)
            gs.append(th)
            sq = sq + s
        factor = clip / jnp.maximum(jnp.sqrt(sq), clip)
        count_inc, bc1, bc2, step_size, sched_inc = adam_scalars(
            adam_st.count, sched_st.count, sched
        )
        outs = [
            fused_adam_update(t, pv, mu, nu, bc1=bc1, bc2=bc2,
                              step_size=step_size, lr_scale=one,
                              factor=factor)
            for t, pv, mu, nu in zip(
                gs, jax.tree.leaves(p),
                jax.tree.leaves(adam_st.mu), jax.tree.leaves(adam_st.nu),
            )
        ]
        new_p = jax.tree.unflatten(g_def, [o[0] for o in outs])
        new_st = (optax.EmptyState(), (
            optax.ScaleByAdamState(
                count=count_inc,
                mu=jax.tree.unflatten(g_def, [o[1] for o in outs]),
                nu=jax.tree.unflatten(g_def, [o[2] for o in outs]),
            ),
            optax.ScaleByScheduleState(count=sched_inc),
        ))
        return new_p, new_st

    adam_ref_us = best_us(ref_tail, grads, params, opt_state)
    adam_fused_us = best_us(fused_tail, grads, params, opt_state)
    adam_parity = bits_equal(
        jax.jit(ref_tail)(grads, params, opt_state),
        jax.jit(fused_tail)(grads, params, opt_state),
    )
    kernels["fused_adam"] = {
        "n_params": int(sum(np.prod(s) for s in shapes.values())),
        "n_leaves": len(shapes),
        "reference_us": adam_ref_us,
        "kernel_us": adam_fused_us,
        "speedup": round(adam_ref_us / max(adam_fused_us, 1e-9), 3),
        "trajectory_parity": adam_parity,
    }

    # ---- (c) int8 weight-quantized matmul at a decode-like shape.
    m, k, n = 8, 256, 1024
    x = jax.random.normal(jax.random.PRNGKey(2), (m, k), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(3), (k, n), jnp.float32) * 0.1
    w_q, scale = quantize_per_channel(w)
    fp32_us = best_us(lambda a, bm: a @ bm, x, w)
    int8_us = best_us(int8_matmul, x, w_q, scale)
    y_fp = np.asarray(x @ w)
    y_q = np.asarray(int8_matmul(x, w_q, scale))
    int8_parity = bits_equal(
        int8_matmul(x, w_q, scale, implementation="pallas",
                    interpret=True),
        int8_matmul(x, w_q, scale, implementation="reference"),
    )
    kernels["int8_matmul"] = {
        "shape": {"m": m, "k": k, "n": n},
        "reference_us": fp32_us,   # the fp32 Dense this path replaces
        "kernel_us": int8_us,
        "speedup": round(fp32_us / max(int8_us, 1e-9), 3),
        "interpret_parity": int8_parity,
        "max_abs_err": round(float(np.abs(y_fp - y_q).max()), 5),
        "argmax_agreement": round(
            float((y_fp.argmax(-1) == y_q.argmax(-1)).mean()), 4
        ),
    }

    # ---- decode leg: the real engine, gather+flash vs paged_kernel.
    model = get_model("gpt2_tiny", max_len=64)
    variables = model.init(
        {"params": jax.random.PRNGKey(0)}, np.zeros((1, 8), np.int32),
        train=False,
    )
    rng = np.random.default_rng(0)
    prompts = [
        np.asarray(rng.integers(0, 1024, ln), np.int32)
        for ln in (5, 3, 12, 7, 17, 9)
    ]

    def run_requests(paged_kernel):
        outs = []
        with Server(model, variables, max_batch=4, kv_page_size=16,
                    paged_kernel=paged_kernel) as server:
            streams = [
                server.submit(p, new_tokens, temperature=0.7, rng=42)
                if i == 3 else server.submit(p, new_tokens)
                for i, p in enumerate(prompts)
            ]
            for s in streams:
                outs.append(np.asarray(s.result(timeout=600)))
        return outs

    compile_watch.install()
    byte_identical = all(
        np.array_equal(a, bmat)
        for a, bmat in zip(run_requests(False), run_requests(True))
    )

    def decode_step_us(paged_kernel, pin=False):
        eng = SlotDecodeEngine(model, variables, max_batch=4,
                               kv_page_size=16,
                               paged_kernel=paged_kernel)
        cache, tok = eng.cache, eng.tok
        for _ in range(warmup):
            cache, tok = eng._decode(
                eng.params, cache, tok, eng._temps, eng._rngs, eng._steps
            )
        jax.block_until_ready(tok)
        if pin:
            compile_watch.mark_warm()
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(iters):
                cache, tok = eng._decode(
                    eng.params, cache, tok, eng._temps, eng._rngs,
                    eng._steps,
                )
            jax.block_until_ready(tok)
            best = min(best, (time.perf_counter() - t0) / iters)
        return round(best * 1e6, 2)

    gather_step_us = decode_step_us(False)
    kernel_step_us = decode_step_us(True, pin=True)
    post_warmup = compile_watch.post_warmup_count()

    decode = {
        "n_requests": len(prompts),
        "new_tokens": new_tokens,
        "byte_identical": byte_identical,
        "gather_step_us": gather_step_us,
        "kernel_step_us": kernel_step_us,
        "kernel_vs_gather": round(
            gather_step_us / max(kernel_step_us, 1e-9), 3
        ),
        "decode_steps_per_sec": round(1e6 / max(kernel_step_us, 1e-9), 1),
        "post_warmup_compiles": post_warmup,
    }
    for name, row in kernels.items():
        print(
            f"# kernels {name:>16} ref {row['reference_us']:>9,.1f} us  "
            f"fused {row['kernel_us']:>9,.1f} us  "
            f"x{row['speedup']:.2f}", flush=True,
        )
    print(
        f"# kernels decode gather {gather_step_us:,.1f} us/step  kernel "
        f"{kernel_step_us:,.1f} us/step  identical={byte_identical}  "
        f"post-warmup compiles={post_warmup}", flush=True,
    )
    result = {
        "model": "gpt2_tiny(max_len=64)",
        "backend": backend,
        "note": (
            "off-TPU every dispatcher resolves 'auto' to its lax "
            "reference, so reference/kernel columns converge by "
            "construction; parity + byte identity are the gate and the "
            "timing columns ratchet the shared program — regenerate on "
            "a chip for the fused-kernel win"
        ),
        "kernels": kernels,
        "decode": decode,
    }
    if out_path:
        _write_kernels_artifact(result, out_path)
    return result


def _write_kernels_artifact(result, out_path) -> None:
    import os

    payload = dict(result)
    payload["generated_by"] = "bench.py --kernels"
    payload["date"] = _utcnow()
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    tmp = out_path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fp:
        json.dump(payload, fp, indent=1)
    os.replace(tmp, out_path)
    print(f"# kernels artifact -> {out_path}", flush=True)


def bench_extended():
    """North-star table, one model per SUBPROCESS so a tunnel hang in any
    single model costs its per-model timeout, not the whole table (round
    3's first attempt lost all four models to one hung init)."""
    import os
    import subprocess

    watchdog = float(os.environ.get("BENCH_WATCHDOG_SECS", "1500"))
    budget = float(
        os.environ.get("EXTENDED_BUDGET_SECS", str(0.6 * watchdog))
    )
    per_model = float(os.environ.get("EXTENDED_PER_MODEL_SECS", "600"))
    t_start = time.time()
    out = []
    for name, (shape, _kind, _kw) in EXTENDED_CONFIGS.items():
        left = budget - (time.time() - t_start)
        if left < 60:
            row = {"model": name, "batch_shape": list(shape),
                   "error": f"SKIPPED: extended budget ({budget:.0f}s) exhausted"}
            out.append(row)
            print(f"# {name} {shape}: {row['error']}")
            continue
        cmd = [sys.executable, __file__, "--one", name, "--assume-up"]
        if jax.default_backend() != "tpu":
            # Propagate the CPU fallback: a child re-runs sitecustomize and
            # would pin the (possibly dead) TPU platform again; env vars
            # don't survive that hook, a flag does.
            cmd.append("--cpu")
        try:
            r = subprocess.run(
                cmd,
                timeout=min(per_model, left), capture_output=True, text=True,
            )
            for line in (r.stderr or "").splitlines():
                if line.startswith("# "):
                    print(line, file=sys.stderr, flush=True)
            parsed = None
            for line in (r.stdout or "").splitlines():
                if line.startswith("{"):
                    parsed = json.loads(line)
            if parsed is None:
                tail = (r.stderr or "").strip().splitlines()
                parsed = {
                    "model": name, "batch_shape": list(shape),
                    "error": f"FAILED: {tail[-1] if tail else 'no output'}",
                }
        except subprocess.TimeoutExpired as e:
            # The child's stderr carries the where-did-it-hang progress
            # lines ('# gpt2: init in ...') — the whole point of the
            # subprocess isolation; keep the tail.
            err_tail = ""
            if e.stderr:
                text = (
                    e.stderr.decode(errors="replace")
                    if isinstance(e.stderr, bytes) else e.stderr
                )
                progress = [
                    ln for ln in text.splitlines() if ln.startswith("# ")
                ]
                err_tail = f" (last: {progress[-1]})" if progress else ""
            parsed = {
                "model": name, "batch_shape": list(shape),
                "error": f"TIMEOUT: > {min(per_model, left):.0f}s "
                         f"(tunnel){err_tail}",
            }
        except Exception as e:
            # One model's subprocess bookkeeping (bad JSON, OS error) must
            # never take down the table or the headline metric.
            parsed = {
                "model": name, "batch_shape": list(shape),
                "error": f"FAILED: {type(e).__name__}: {e}",
            }
        out.append(parsed)
        if "error" in parsed:
            print(f"# {name} {shape}: {parsed['error']}")
        else:
            mfu = parsed.get("mfu")
            mfu_s = f" MFU={mfu * 100:.1f}%" if mfu is not None else ""
            print(
                f"# {name} {shape}: {parsed['samples_per_sec']:,.1f} "
                f"samples/s{mfu_s}"
            )
    return out


def bench_memplan(args) -> dict:
    """``--memplan``: the analytic fit-or-OOM planner.  Prices a model ×
    batch × parallelism config per device (telemetry/memory.py formula
    walk — ``jax.eval_shape`` only) and judges the predicted peak
    against the chip HBM capacity table (telemetry/flops.py)."""
    from ml_trainer_tpu.models.registry import get_model
    from ml_trainer_tpu.telemetry import memory as _memory

    mesh_shape = {}
    for part in (args.memplan_mesh or "").split(","):
        if part.strip():
            axis, _, n = part.partition("=")
            mesh_shape[axis.strip()] = int(n)
    name = args.memplan
    model = get_model(
        name, **(EXTENDED_CONFIGS[name][2]() if name in EXTENDED_CONFIGS
                 else {})
    )
    batch = args.batch_size or (
        EXTENDED_CONFIGS[name][0][0] if name in EXTENDED_CONFIGS else 32
    )
    if name in EXTENDED_CONFIGS:
        shape = (batch,) + tuple(EXTENDED_CONFIGS[name][0][1:])
    elif getattr(model, "max_len", 0):
        shape = (batch, args.memplan_seq or int(model.max_len))
    else:
        shape = (batch, 32, 32, 3)
    ledger = _memory.plan_train_memory(
        model, shape,
        optimizer=args.memplan_optimizer,
        mesh_shape=mesh_shape,
        shard_opt_state=args.memplan_zero1,
        precision=args.memplan_precision,
    )
    verdict = _memory.fit_verdict(ledger.peak_bytes())
    for c in ledger.components:
        print(f"# {c.name:<18} {c.bytes / 2 ** 20:10.2f} MiB  ({c.kind})",
              file=sys.stderr)
    print(
        f"# peak {ledger.peak_bytes() / 2 ** 30:.2f} GiB vs "
        f"{verdict['chip']} capacity "
        f"{verdict['capacity_bytes'] / 2 ** 30:.0f} GiB -> "
        f"{verdict['verdict'].upper()}",
        file=sys.stderr,
    )
    return {
        "model": name, "batch_shape": list(shape),
        "mesh": mesh_shape or {"data": 1},
        "optimizer": args.memplan_optimizer,
        "zero1": bool(args.memplan_zero1),
        "precision": args.memplan_precision,
        "ledger": ledger.as_dict(),
        "fit": verdict,
    }


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--extended", action="store_true",
                        help="also bench the north-star model zoo")
    parser.add_argument("--one", metavar="MODEL", default=None,
                        choices=sorted(EXTENDED_CONFIGS),
                        help="bench a single north-star model, print one "
                        "JSON line (used by --extended's subprocesses)")
    parser.add_argument("--cpu", action="store_true",
                        help="pin the CPU backend (in-process config update "
                        "— the only pin that survives sitecustomize)")
    parser.add_argument("--loaders", action="store_true",
                        help="run only the host input-pipeline benchmark "
                        "(Python vs C++ loader; no device work)")
    parser.add_argument("--spec", action="store_true",
                        help="run only the speculative-decoding benchmark: "
                        "n-gram lookup drafting vs the vanilla compiled "
                        "decode loop on a repetitive greedy workload "
                        "(gpt2_tiny; CPU-safe)")
    parser.add_argument("--dispatch", action="store_true",
                        help="run only the pjit dispatch microbenchmark: "
                        "per-call host overhead of the compiled train and "
                        "decode steps (CPU-safe)")
    parser.add_argument("--chaos", action="store_true",
                        help="run only the chaos/recovery benchmark: "
                        "step-checkpoint overhead, steps lost on "
                        "preemption, time-to-recover (MLModel; CPU-safe)")
    parser.add_argument("--telemetry", action="store_true",
                        help="run only the telemetry-overhead benchmark: "
                        "instrumented (Trainer(telemetry=True)) vs bare "
                        "step time on the CPU mlmodel row (target <2%% "
                        "overhead; CPU-safe)")
    parser.add_argument("--serve", action="store_true",
                        help="run only the serving benchmark: the "
                        "continuous-batching engine vs a generate_ragged "
                        "dynamic-batching baseline on ragged Poisson "
                        "arrivals (gpt2_tiny; CPU-safe)")
    parser.add_argument("--serve-replay", action="store_true",
                        help="run only the multi-tenant ragged replay: "
                        "the PAGED engine (page pool + prefix cache + "
                        "tenant scheduler) vs the contiguous engine on an "
                        "80%%-shared-prefix Poisson trace; writes the "
                        "docs/serving_replay_cpu.json artifact "
                        "(gpt2_tiny; CPU-safe)")
    parser.add_argument("--slo", action="store_true",
                        help="run only the open-loop SLO sweep: fixed "
                        "Poisson arrival schedules at >=3 offered rates "
                        "through the real HTTP server, TTFT/TPOT/queue-"
                        "wait/e2e p50+p99 with SLO attainment + burn rate "
                        "per rate, zero recompiles pinned; writes "
                        "docs/serving_slo_cpu.json (gpt2_tiny; CPU-safe)")
    parser.add_argument("--slo-url", default=None, metavar="URL",
                        help="point the --slo sweep's schedules at an "
                        "EXTERNAL target URL (a single replica's front "
                        "end or the disaggregated router's) instead of "
                        "building a local server; no artifact written")
    parser.add_argument("--serve-lora", action="store_true",
                        help="run only the batched-LoRA serving leg: 64 "
                        "concurrent adapters over one gpt2 base, open-"
                        "loop at saturating load vs the single-model "
                        "baseline on the identical schedule; adapter="
                        "None byte identity, mid-run hot-load and zero "
                        "recompiles pinned; writes "
                        "docs/serving_lora_cpu.json (gpt2_tiny; CPU-safe)")
    parser.add_argument("--serve-lora-url", default=None, metavar="URL",
                        help="point the --serve-lora schedule at an "
                        "EXTERNAL target URL (a replica's front end or "
                        "an adapter-pooled router fleet's) instead of "
                        "building a local server; no artifact written")
    parser.add_argument("--serve-disagg", action="store_true",
                        help="run only the disaggregated-vs-colocated "
                        "router comparison: the same recorded 80%%-"
                        "shared-prefix trace open-loop at saturating "
                        "load through a 2-prefill+2-decode router with "
                        "page-granular KV migration vs 4 colocated "
                        "replicas; byte identity + zero recompiles "
                        "pinned; writes docs/serving_disagg_cpu.json "
                        "(gpt2_tiny; CPU-safe)")
    parser.add_argument("--serve-fleet", action="store_true",
                        help="run only the multi-process fleet bench: "
                        "4 worker PROCESSES behind the socket router, "
                        "chunked prefill on a long+short mix vs "
                        "short-only and unchunked fleets, a real "
                        "SIGKILL + autoscaler respawn; byte identity + "
                        "zero per-process recompiles pinned; writes "
                        "docs/serving_fleet_cpu.json "
                        "(gpt2_tiny; CPU-safe)")
    parser.add_argument("--fleet-obs", action="store_true",
                        help="run only the fleet-observability-plane "
                        "bench: a 3-process fleet under the router's "
                        "metrics federation + cross-process tracing + "
                        "incident bundling, measuring scrape/render/"
                        "trace-merge/bundle latency and pinning the "
                        "plane's invariants (labelled worker series, "
                        "idempotent re-scrape, >= 2 causal trace "
                        "lanes, complete bundle, byte identity, zero "
                        "recompiles); writes docs/fleet_obs_cpu.json "
                        "(gpt2_tiny; CPU-safe)")
    parser.add_argument("--watchtower", action="store_true",
                        help="run only the watchtower bench: the "
                        "in-process TSDB + alert engine + dashboard "
                        "measured on a serving-worker-sized registry "
                        "(sample/ingest/eval/query/render per-call ms) "
                        "with the one-eval-window regression-detection, "
                        "ring-bound and dump-roundtrip invariants "
                        "pinned; writes docs/watchtower_cpu.json "
                        "(pure host; CPU-safe)")
    parser.add_argument("--serve-deploy", action="store_true",
                        help="run only the live-rollout bench: train a "
                        "tiny gpt2 in-bench, export it, and deploy the "
                        "export onto a 2-process fleet MID-LOAD (canary "
                        "-> ramp -> promote), then force a canary "
                        "regression through a wedged factory and pin "
                        "the SLO-burn auto-rollback; zero dropped "
                        "streams, byte identity and zero steady-fleet "
                        "recompiles pinned; writes "
                        "docs/serving_deploy_cpu.json "
                        "(gpt2_tiny; CPU-safe)")
    parser.add_argument("--serve-chaos", action="store_true",
                        help="run only the serving-chaos leg: the recorded "
                        "80%%-shared-prefix trace open-loop at saturating "
                        "load through a 2-prefill+2-decode router while "
                        "1-of-4 replicas is killed and another slowed "
                        "mid-run, with vs without the mitigation stack "
                        "(SLO-burn autoscaler + hedged prefills + circuit "
                        "breakers + degradation ladder); attainment >= 2x "
                        "baseline, byte identity, zero recompiles and "
                        "structured failures pinned; writes "
                        "docs/serving_chaos_cpu.json (gpt2_tiny; CPU-safe)")
    parser.add_argument("--mixed", action="store_true",
                        help="run only the mixed-precision / sharded-update "
                        "matrix: {fp32,bf16} x {fused-psum, bucketed "
                        "reduce-scatter + sharded update} step time and "
                        "comm bytes on a virtual pure-DP mesh (the "
                        "dryrun_multichip style; writes "
                        "docs/mixed_precision_cpu.json; CPU-safe)")
    parser.add_argument("--mixed-devices", type=int, default=8,
                        help="virtual device count for --mixed (default 8)")
    parser.add_argument("--pipeline", action="store_true",
                        help="run only the pipeline-schedule matrix: "
                        "gpipe vs 1f1b vs interleaved vs zb step time, "
                        "analytic bubble fractions, per-hop comm bytes "
                        "and serial-fold equality on a virtual stage "
                        "mesh (writes docs/pipeline_schedules_cpu.json; "
                        "CPU-safe)")
    parser.add_argument("--pipeline-devices", type=int, default=4,
                        help="virtual device count for --pipeline "
                        "(default 4)")
    parser.add_argument("--kernels", action="store_true",
                        help="run only the ops/kernels/ Pallas-pass leg: "
                        "per-kernel reference-vs-dispatch microbench "
                        "(paged attention, fused Adam tail, int8 matmul) "
                        "with interpret-mode parity, plus the real-engine "
                        "gather-vs-paged_kernel decode comparison — byte "
                        "identity and zero post-warmup recompiles pinned; "
                        "writes docs/kernels_cpu.json (gpt2_tiny; "
                        "CPU-safe)")
    parser.add_argument("--memplan", metavar="MODEL", default=None,
                        help="fit-or-OOM planner (telemetry/memory.py): "
                        "analytic per-device HBM ledger for MODEL under "
                        "the given knobs, judged against the chip's HBM "
                        "capacity — no state is built, no device memory "
                        "touched (CPU-safe; works for topologies this "
                        "host does not have)")
    parser.add_argument("--memplan-mesh", default="",
                        help="mesh for --memplan as 'data=8' or "
                        "'data=4,tensor=2' (default: single device)")
    parser.add_argument("--memplan-optimizer", default="adamw",
                        help="optimizer whose moments the --memplan "
                        "ledger prices (default adamw)")
    parser.add_argument("--memplan-zero1", action="store_true",
                        help="price ZeRO-1 moment sharding (÷data) in "
                        "--memplan")
    parser.add_argument("--memplan-precision", default=None,
                        help="compute precision for --memplan (e.g. bf16)")
    parser.add_argument("--memplan-seq", type=int, default=None,
                        help="sequence length override for --memplan LM "
                        "models (default: the model's max_len)")
    parser.add_argument("--assume-up", action="store_true",
                        help="skip the --one pre-probe (used by --extended, "
                        "whose parent just probed — a second throwaway "
                        "backend init would come out of the per-model "
                        "timeout)")
    parser.add_argument("--reconcile", action="store_true",
                        help="measure BOTH dispatch paths (per-batch and "
                        "multi-step) in one session with the fenced timer "
                        "and report them side by side")
    parser.add_argument("--batch_size", type=int, default=None,
                        help="override the batch size (headline MLModel "
                        "bench defaults to 32; --one rows default to their "
                        "EXTENDED_CONFIGS shape)")
    args = parser.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    if args.memplan:
        print(json.dumps({"memplan": bench_memplan(args)}, indent=1))
        return
    if not args.one:
        args.batch_size = args.batch_size or 32
    if args.one:
        if not args.cpu and not args.assume_up:
            # Probe in a killable subprocess first: a wedged tunnel hangs
            # at backend init, which would otherwise burn the caller's
            # full per-model timeout before it learns anything.  Take the
            # host-wide tunnel lock first (held to exit) so this dial
            # cannot race the watcher's.
            lock_log: list = []
            if not _acquire_tunnel_lock(time.time() + 300.0, lock_log):
                print(json.dumps(
                    {"model": args.one,
                     "error": "FAILED: tunnel lock held by another client",
                     "probe": lock_log}
                ), flush=True)
                sys.exit(1)
            note = _probe_backend_subprocess(timeout=240.0)
            if note:
                print(json.dumps(
                    {"model": args.one, "error": f"FAILED: {note}"}
                ), flush=True)
                sys.exit(1)
        print(json.dumps(bench_one_model(args.one, args.batch_size)),
              flush=True)
        return
    if args.loaders:
        # Host-side only: measures the input pipeline, touches no device,
        # so it is safe (and meaningful) while the TPU tunnel is down.
        bench_loaders()
        return
    if args.chaos:
        # Recovery-overhead leg; tiny model, any backend — plus the
        # elastic leg: kill 1 of N simulated hosts mid-run and measure
        # the reshape downtime / steps-lost / time-to-recover the
        # committed docs/elastic_chaos_cpu.json artifact pins.
        print(json.dumps({"chaos": bench_chaos(), "elastic": bench_elastic()}))
        return
    if args.telemetry:
        # Instrumented-vs-bare step time; tiny model, any backend.
        print(json.dumps({"telemetry": bench_telemetry()}))
        return
    if args.serve:
        # Tiny model; meaningful on any backend.  One JSON line for the
        # driver, engine-vs-baseline, like the headline metric.
        print(json.dumps({"serve": bench_serve()}))
        return
    if args.serve_replay:
        # Paged vs contiguous engine on the multi-tenant shared-prefix
        # trace; the artifact is the acceptance evidence for the paged
        # KV subsystem and feeds scripts/bench_gate.py.
        import os as _os

        out = _os.path.join(
            _os.path.dirname(_os.path.abspath(__file__)),
            "docs", "serving_replay_cpu.json",
        )
        result = bench_serve_replay(out_path=out)
        print(json.dumps({"serve_replay": result}))
        if result.get("error"):
            sys.exit(1)
        return
    if args.slo:
        # Open-loop capacity-vs-SLO sweep through the real HTTP server;
        # the artifact is what scripts/bench_gate.py gate_slo ratchets.
        # --slo-url redirects the same schedules at an external target
        # (router or replica) with client-side truth, no artifact.
        import os as _os

        out = None if args.slo_url else _os.path.join(
            _os.path.dirname(_os.path.abspath(__file__)),
            "docs", "serving_slo_cpu.json",
        )
        result = bench_slo(out_path=out, target_url=args.slo_url)
        print(json.dumps({"slo": result}))
        if result.get("error"):
            sys.exit(1)
        return
    if args.serve_lora or args.serve_lora_url:
        # 64 concurrent LoRA adapters over one base vs the single-model
        # baseline; the artifact is the acceptance evidence for the
        # batched-adapter subsystem and feeds bench_gate.py gate_lora.
        # --serve-lora-url redirects the schedule at an external target
        # (e.g. a router fleet with adapter pools), client-side truth.
        import os as _os

        out = None if args.serve_lora_url else _os.path.join(
            _os.path.dirname(_os.path.abspath(__file__)),
            "docs", "serving_lora_cpu.json",
        )
        result = bench_serve_lora(
            out_path=out, target_url=args.serve_lora_url
        )
        print(json.dumps({"serve_lora": result}))
        if result.get("error"):
            sys.exit(1)
        return
    if args.serve_disagg:
        # Disaggregated vs colocated router at equal replica count; the
        # artifact is the acceptance evidence for the router subsystem
        # and feeds scripts/bench_gate.py gate_disagg.
        import os as _os

        out = _os.path.join(
            _os.path.dirname(_os.path.abspath(__file__)),
            "docs", "serving_disagg_cpu.json",
        )
        result = bench_serve_disagg(out_path=out)
        print(json.dumps({"serve_disagg": result}))
        if result.get("error"):
            sys.exit(1)
        return
    if args.serve_fleet:
        # True multi-process fleet: socket-only router, chunked
        # prefill, SIGKILL survival; the artifact is the acceptance
        # evidence for serving/fleet.py and feeds bench_gate.py
        # gate_fleet.
        import os as _os

        out = _os.path.join(
            _os.path.dirname(_os.path.abspath(__file__)),
            "docs", "serving_fleet_cpu.json",
        )
        result = bench_serve_fleet(out_path=out)
        print(json.dumps({"serve_fleet": result}))
        if result.get("error"):
            sys.exit(1)
        return
    if args.fleet_obs:
        # Fleet observability plane: federation + tracing + bundles on
        # a real 3-process fleet; the artifact is the acceptance
        # evidence for the plane's overhead and feeds bench_gate.py
        # gate_fleet.
        import os as _os

        out = _os.path.join(
            _os.path.dirname(_os.path.abspath(__file__)),
            "docs", "fleet_obs_cpu.json",
        )
        result = bench_fleet_obs(out_path=out)
        print(json.dumps({"fleet_obs": result}))
        if result.get("error"):
            sys.exit(1)
        return
    if args.watchtower:
        # Watchtower TSDB + alert engine + dashboard overhead; the
        # artifact is the acceptance evidence for the fourth
        # observability pillar and feeds bench_gate.py gate_watchtower.
        import os as _os

        out = _os.path.join(
            _os.path.dirname(_os.path.abspath(__file__)),
            "docs", "watchtower_cpu.json",
        )
        result = bench_watchtower(out_path=out)
        print(json.dumps({"watchtower": result}))
        if result.get("error"):
            sys.exit(1)
        return
    if args.serve_deploy:
        # Live base-model rollout under traffic: canary + auto-rollback
        # on a real multi-process fleet; the artifact is the acceptance
        # evidence for serving/deploy.py and feeds bench_gate.py
        # gate_deploy.
        import os as _os

        out = _os.path.join(
            _os.path.dirname(_os.path.abspath(__file__)),
            "docs", "serving_deploy_cpu.json",
        )
        result = bench_serve_deploy(out_path=out)
        print(json.dumps({"serve_deploy": result}))
        if result.get("error"):
            sys.exit(1)
        return
    if args.serve_chaos:
        # Serving fleet under chaos (kill + slow) with vs without the
        # mitigation stack; the artifact is the acceptance evidence for
        # the overload subsystem and feeds bench_gate.py gate_overload.
        import os as _os

        out = _os.path.join(
            _os.path.dirname(_os.path.abspath(__file__)),
            "docs", "serving_chaos_cpu.json",
        )
        result = bench_serve_chaos(out_path=out)
        print(json.dumps({"serve_chaos": result}))
        if result.get("error"):
            sys.exit(1)
        return
    if args.mixed:
        # Mixed-precision / sharded-update matrix on virtual devices.
        # The respawned child (env marker) must not write the artifact —
        # its parent does, after validating the child's JSON.
        import os as _os

        child = _os.environ.get("ML_TRAINER_TPU_MIXED_CHILD") == "1"
        out = None if child else _os.path.join(
            _os.path.dirname(_os.path.abspath(__file__)),
            "docs", "mixed_precision_cpu.json",
        )
        result = bench_mixed(n_devices=args.mixed_devices, out_path=out)
        print(json.dumps({"mixed": result}), flush=True)
        if result.get("error"):
            sys.exit(1)
        return
    if args.pipeline:
        # Pipeline-schedule matrix on virtual stage devices.  Like
        # --mixed, the respawned child (env marker) must not write the
        # artifact — its parent does, after validating the child's JSON.
        import os as _os

        child = _os.environ.get("ML_TRAINER_TPU_PIPELINE_CHILD") == "1"
        out = None if child else _os.path.join(
            _os.path.dirname(_os.path.abspath(__file__)),
            "docs", "pipeline_schedules_cpu.json",
        )
        result = bench_pipeline(
            n_devices=args.pipeline_devices, out_path=out
        )
        print(json.dumps({"pipeline": result}), flush=True)
        if result.get("error"):
            sys.exit(1)
        return
    if args.kernels:
        # Kernel-pass microbench + engine decode comparison; the
        # artifact is the acceptance evidence for ops/kernels/ and
        # feeds scripts/bench_gate.py gate_kernels.
        import os as _os

        out = _os.path.join(
            _os.path.dirname(_os.path.abspath(__file__)),
            "docs", "kernels_cpu.json",
        )
        result = bench_kernels(out_path=out)
        print(json.dumps({"kernels": result}), flush=True)
        if result.get("error"):
            sys.exit(1)
        return
    if args.spec:
        # Speculative vs vanilla decode; tiny model, any backend.
        print(json.dumps({"spec": bench_spec()}))
        return
    if args.dispatch:
        # Host dispatch overhead canary; touches a trivial program only.
        print(json.dumps({"dispatch": bench_dispatch()}))
        return
    record = {
        "metric": (
            f"train_samples_per_sec (MLModel/CIFAR-10, bs={args.batch_size}, "
            "full train step)"
        ),
        "value": None,
        "unit": "samples/s",
        "vs_baseline": None,
    }
    # Last line of defense: if anything past the probe hangs (remote-compile
    # tunnel), still emit the JSON record before the driver's kill timer.
    import os as _os
    import threading

    watchdog_secs = float(_os.environ.get("BENCH_WATCHDOG_SECS", "1500"))

    def _fire():
        record["error"] = (
            f"watchdog: bench exceeded {watchdog_secs:.0f}s "
            "(TPU tunnel hang?)"
        )
        print(json.dumps(record), flush=True)
        _os._exit(1)

    watchdog = threading.Timer(watchdog_secs, _fire)
    watchdog.daemon = True
    watchdog.start()
    try:
        if args.cpu:
            # Pinned CPU: probing the default (TPU) backend would dial the
            # tunnel this flag exists to avoid.
            devices, note = jax.devices(), "CPU-pinned run (--cpu)"
        else:
            devices, note, probe_log = _init_devices_with_retry()
            record["backend"] = "cpu" if note else "tpu"
            record["probe"] = probe_log
        print(f"# devices: {devices}", file=sys.stderr)
        if note:
            record["note"] = note
        if args.extended:
            bench_loaders()
            record["extended"] = bench_extended()
        if args.reconcile:
            # Same session, same fenced StepTimer, both dispatch paths —
            # the only honest way to compare them (numbers from different
            # sessions/fences produced a 3x contradiction in round 2).
            # The per-batch result is written into the record IMMEDIATELY
            # so a hang/exception in the second pass cannot lose it.
            per_batch = bench_parity(args.batch_size, steps_per_execution=1)
            record["per_batch_samples_per_sec"] = round(per_batch, 1)
            print(f"# reconcile per-batch: {per_batch:,.1f} samples/s",
                  flush=True)
            k = _effective_k(args.batch_size)
            if k > 1:
                samples_per_sec = bench_parity(args.batch_size)
                print(f"# reconcile multi-step (k={k}): "
                      f"{samples_per_sec:,.1f} samples/s "
                      f"({samples_per_sec / per_batch:.2f}x per-batch)",
                      flush=True)
            else:
                print("# reconcile: multi-step collapses to k=1 at batch "
                      f"{args.batch_size} — single path, nothing to compare",
                      flush=True)
                samples_per_sec = per_batch
        else:
            samples_per_sec = bench_parity(args.batch_size)
        record["value"] = round(samples_per_sec, 1)
        record["vs_baseline"] = round(
            samples_per_sec / BASELINE_SAMPLES_PER_SEC, 2
        )
    except Exception as e:
        # The driver must ALWAYS get a parseable JSON line, even on failure.
        record["error"] = f"{type(e).__name__}: {e}"
    watchdog.cancel()
    print(json.dumps(record))
    if "error" in record:
        sys.exit(1)


if __name__ == "__main__":
    main()
