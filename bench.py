"""Benchmark harness — one JSON line for the driver.

Metric: training throughput (samples/sec) of the reference parity workload —
MLModel (LeNet) on CIFAR-10-shaped data at global batch 32, full train step
(forward, loss, backward, SGD update + on-device metric), driven through the
framework's Trainer machinery (prefetched Loader + compiled step), i.e. the
exact configuration behind the reference's only recorded number:
822–966 samples/s on local CPU (01 nb cell-12; BASELINE.md).  ``vs_baseline``
divides by the best reference figure (966).

Run ``python bench.py --extended`` for the north-star model table
(ResNet-50, ViT-B/16, BERT-base, GPT-2-124M step throughput) printed as
extra human-readable lines before the JSON line.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from ml_trainer_tpu.trainer import enable_compilation_cache
from ml_trainer_tpu.utils.profiler import StepTimer

enable_compilation_cache()

BASELINE_SAMPLES_PER_SEC = 966.0  # reference train throughput, BASELINE.md


def _probe_backend_subprocess(timeout: float) -> str:
    """Try initializing the default backend in a THROWAWAY subprocess.

    The TPU tunnel here can hang at init (not just error) — r01's records
    show both modes.  A hang inside this process would wedge it past any
    retry logic, so the probe runs where it can be killed.  Returns "" on
    success or a failure description.
    """
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(len(jax.devices()), jax.default_backend())"],
            timeout=timeout, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        return f"backend init hang (> {timeout:.0f}s)"
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        return f"backend init error: {tail[-1] if tail else 'rc=' + str(r.returncode)}"
    print(f"# backend probe OK: {r.stdout.strip()}", file=sys.stderr)
    return ""


def _init_devices_with_retry(max_attempts=3, probe_timeout=240.0):
    """Initialize the JAX backend, surviving TPU UNAVAILABLE errors AND
    init hangs.  Probes in a subprocess first (killable), retries with
    backoff, and finally falls back to CPU so the driver always gets a
    parseable JSON line.  Returns (devices, note)."""
    last = ""
    for attempt in range(1, max_attempts + 1):
        last = _probe_backend_subprocess(probe_timeout)
        if not last:
            return jax.devices(), ""
        print(
            f"# backend probe {attempt}/{max_attempts} failed: {last}",
            file=sys.stderr,
        )
        if attempt < max_attempts:
            time.sleep(min(5.0 * 2 ** (attempt - 1), 30.0))
    # Fall back to CPU in-process: safe because this process has not touched
    # the default backend yet.
    jax.config.update("jax_platforms", "cpu")
    return jax.devices(), f"TPU unavailable ({last}); measured on CPU fallback"


def _steady_state_rate(step, state, batches, warmup=5, iters=50):
    """Steps/sec via the fenced StepTimer (compile/warmup excluded)."""
    timer = StepTimer(warmup=warmup)
    for i in range(warmup + iters):
        state, *_ = step(state, *batches[i % len(batches)])
        timer.tick(state, 1)
    return timer.rate(), state


def bench_parity(batch_size=32):
    """The reference workload through the real Trainer train step."""
    from ml_trainer_tpu import Trainer, MLModel
    from ml_trainer_tpu.data import SyntheticCIFAR10
    from ml_trainer_tpu.utils.functions import custom_pre_process_function

    ds = SyntheticCIFAR10(size=2048, transform=custom_pre_process_function())
    trainer = Trainer(
        MLModel(), datasets=(ds, ds), epochs=1, batch_size=batch_size,
        model_dir="/tmp/bench_model", metric="accuracy", lr=0.01,
    )
    # Pre-materialize transformed device batches so we measure the compiled
    # step (the input pipeline overlaps via prefetch during real training).
    from ml_trainer_tpu.data import prefetch_to_device

    batches = [
        (x, y, jnp.asarray(1.0, jnp.float32))
        for _, (x, y) in zip(
            range(16),
            prefetch_to_device(
                trainer.train_loader, size=2, sharding=trainer._batch_sharding
            ),
        )
    ]
    rate, _ = _steady_state_rate(trainer._train_step, trainer.state, batches)
    return rate * batch_size


def bench_loaders(size=4096, batch_size=256, epochs=4):
    """Host input-pipeline throughput: Python Loader vs native C++ worker,
    same fused augmentation (crop/flip/normalize)."""
    from ml_trainer_tpu.data import Loader, SyntheticCIFAR10
    from ml_trainer_tpu.data.native import NativeLoader, native_available
    from ml_trainer_tpu.utils.functions import custom_pre_process_function

    ds = SyntheticCIFAR10(size=size, transform=custom_pre_process_function())

    def rate(loader):
        list(loader)  # warm (build lib / allocate)
        t0 = time.perf_counter()
        n = 0
        for _ in range(epochs):
            for x, _y in loader:
                n += x.shape[0]
        return n / (time.perf_counter() - t0)

    py = rate(Loader(ds, batch_size=batch_size, shuffle=True, seed=0))
    print(f"# input pipeline python: {py:,.0f} samples/s")
    if native_available():
        nat = rate(NativeLoader(ds, batch_size=batch_size, seed=0))
        print(
            f"# input pipeline native (C++): {nat:,.0f} samples/s "
            f"({nat / py:.2f}x python)"
        )


def bench_extended():
    """North-star models: one full train step, steady-state steps/sec."""
    import optax

    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.ops import get_criterion, get_optimizer
    from ml_trainer_tpu.train_state import TrainState

    configs = [
        ("resnet50", dict(), (32, 224, 224, 3), "image", jnp.bfloat16),
        ("vit_b16", dict(num_classes=1000), (32, 224, 224, 3), "image", jnp.bfloat16),
        ("bert_base", dict(num_classes=2), (32, 128), "tokens", None),
        ("gpt2", dict(), (8, 1024), "lm", None),
    ]
    rows = []
    for name, kw, shape, kind, in_dtype in configs:
        try:
            model = get_model(name, **kw)
            rng = np.random.default_rng(0)
            if kind == "image":
                x = jnp.asarray(rng.normal(size=shape), dtype=in_dtype or jnp.float32)
                y = jnp.asarray(rng.integers(0, 10, shape[0]), jnp.int32)
            else:
                x = jnp.asarray(rng.integers(0, 1000, shape), jnp.int32)
                y = (
                    jnp.roll(x, -1, axis=1)
                    if kind == "lm"
                    else jnp.asarray(rng.integers(0, 2, shape[0]), jnp.int32)
                )
            variables = model.init(
                {"params": jax.random.PRNGKey(0)}, x, train=False
            )
            params = variables["params"]
            batch_stats = variables.get("batch_stats", {})
            tx = get_optimizer("adamw", 1e-4)
            criterion = get_criterion("cross_entropy")
            state = TrainState(
                step=jnp.zeros((), jnp.int32), params=params,
                opt_state=tx.init(params), batch_stats=batch_stats,
                rng=jax.random.PRNGKey(1),
            )
            has_bs = bool(batch_stats)

            @jax.jit
            def step(state, x, y):
                def loss_fn(p):
                    if has_bs:
                        out, mut = model.apply(
                            {"params": p, "batch_stats": state.batch_stats},
                            x, train=True, mutable=["batch_stats"],
                        )
                        return criterion(out, y), mut["batch_stats"]
                    out = model.apply({"params": p}, x, train=True)
                    return criterion(out, y), state.batch_stats

                (loss, new_bs), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(state.params)
                updates, opt_state = tx.update(
                    grads, state.opt_state, state.params
                )
                return (
                    state.replace(
                        step=state.step + 1,
                        params=optax.apply_updates(state.params, updates),
                        opt_state=opt_state,
                        batch_stats=new_bs,
                    ),
                    loss,
                )

            rate, _ = _steady_state_rate(
                step, state, [(x, y)], warmup=3, iters=20
            )
            rows.append((name, shape, rate * shape[0]))
        except Exception as e:  # keep the headline metric robust
            rows.append((name, shape, f"FAILED: {type(e).__name__}: {e}"))
    for name, shape, rate in rows:
        if isinstance(rate, float):
            print(f"# {name} {shape}: {rate:,.1f} samples/s")
        else:
            print(f"# {name} {shape}: {rate}")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--extended", action="store_true",
                        help="also bench the north-star model zoo")
    parser.add_argument("--batch_size", type=int, default=32)
    args = parser.parse_args()
    record = {
        "metric": "train_samples_per_sec (MLModel/CIFAR-10, bs=32, full train step)",
        "value": None,
        "unit": "samples/s",
        "vs_baseline": None,
    }
    # Last line of defense: if anything past the probe hangs (remote-compile
    # tunnel), still emit the JSON record before the driver's kill timer.
    import os as _os
    import threading

    watchdog_secs = float(_os.environ.get("BENCH_WATCHDOG_SECS", "1500"))

    def _fire():
        record["error"] = (
            f"watchdog: bench exceeded {watchdog_secs:.0f}s "
            "(TPU tunnel hang?)"
        )
        print(json.dumps(record), flush=True)
        _os._exit(1)

    watchdog = threading.Timer(watchdog_secs, _fire)
    watchdog.daemon = True
    watchdog.start()
    try:
        devices, note = _init_devices_with_retry()
        print(f"# devices: {devices}", file=sys.stderr)
        if note:
            record["note"] = note
        if args.extended:
            bench_loaders()
            bench_extended()
        samples_per_sec = bench_parity(args.batch_size)
        record["value"] = round(samples_per_sec, 1)
        record["vs_baseline"] = round(
            samples_per_sec / BASELINE_SAMPLES_PER_SEC, 2
        )
    except Exception as e:
        # The driver must ALWAYS get a parseable JSON line, even on failure.
        record["error"] = f"{type(e).__name__}: {e}"
    watchdog.cancel()
    print(json.dumps(record))
    if "error" in record:
        sys.exit(1)


if __name__ == "__main__":
    main()
