"""Benchmark harness — one JSON line for the driver.

Metric: training throughput (samples/sec) of the reference parity workload —
MLModel (LeNet) on CIFAR-10-shaped data at global batch 32, full train step
(forward, loss, backward, SGD update + on-device metric), driven through the
framework's Trainer machinery (prefetched Loader + compiled step), i.e. the
exact configuration behind the reference's only recorded number:
822–966 samples/s on local CPU (01 nb cell-12; BASELINE.md).  ``vs_baseline``
divides by the best reference figure (966).

Run ``python bench.py --extended`` for the north-star model table
(ResNet-50, ViT-B/16, BERT-base, GPT-2-124M step throughput) printed as
extra human-readable lines before the JSON line.
"""

from __future__ import annotations

import argparse
import json

import jax
import jax.numpy as jnp
import numpy as np

from ml_trainer_tpu.trainer import enable_compilation_cache
from ml_trainer_tpu.utils.profiler import StepTimer

enable_compilation_cache()

BASELINE_SAMPLES_PER_SEC = 966.0  # reference train throughput, BASELINE.md


def _steady_state_rate(step, state, batches, warmup=5, iters=50):
    """Steps/sec via the fenced StepTimer (compile/warmup excluded)."""
    timer = StepTimer(warmup=warmup)
    for i in range(warmup + iters):
        state, *_ = step(state, *batches[i % len(batches)])
        timer.tick(state, 1)
    return timer.rate(), state


def bench_parity(batch_size=32):
    """The reference workload through the real Trainer train step."""
    from ml_trainer_tpu import Trainer, MLModel
    from ml_trainer_tpu.data import SyntheticCIFAR10
    from ml_trainer_tpu.utils.functions import custom_pre_process_function

    ds = SyntheticCIFAR10(size=2048, transform=custom_pre_process_function())
    trainer = Trainer(
        MLModel(), datasets=(ds, ds), epochs=1, batch_size=batch_size,
        model_dir="/tmp/bench_model", metric="accuracy", lr=0.01,
    )
    # Pre-materialize transformed device batches so we measure the compiled
    # step (the input pipeline overlaps via prefetch during real training).
    from ml_trainer_tpu.data import prefetch_to_device

    batches = [
        (x, y, jnp.asarray(1.0, jnp.float32))
        for _, (x, y) in zip(
            range(16),
            prefetch_to_device(
                trainer.train_loader, size=2, sharding=trainer._batch_sharding
            ),
        )
    ]
    rate, _ = _steady_state_rate(trainer._train_step, trainer.state, batches)
    return rate * batch_size


def bench_extended():
    """North-star models: one full train step, steady-state steps/sec."""
    import optax

    from ml_trainer_tpu.models import get_model
    from ml_trainer_tpu.ops import get_criterion, get_optimizer
    from ml_trainer_tpu.train_state import TrainState

    configs = [
        ("resnet50", dict(), (32, 224, 224, 3), "image", jnp.bfloat16),
        ("vit_b16", dict(num_classes=1000), (32, 224, 224, 3), "image", jnp.bfloat16),
        ("bert_base", dict(num_classes=2), (32, 128), "tokens", None),
        ("gpt2", dict(), (8, 1024), "lm", None),
    ]
    rows = []
    for name, kw, shape, kind, in_dtype in configs:
        try:
            model = get_model(name, **kw)
            rng = np.random.default_rng(0)
            if kind == "image":
                x = jnp.asarray(rng.normal(size=shape), dtype=in_dtype or jnp.float32)
                y = jnp.asarray(rng.integers(0, 10, shape[0]), jnp.int32)
            else:
                x = jnp.asarray(rng.integers(0, 1000, shape), jnp.int32)
                y = (
                    jnp.roll(x, -1, axis=1)
                    if kind == "lm"
                    else jnp.asarray(rng.integers(0, 2, shape[0]), jnp.int32)
                )
            variables = model.init(
                {"params": jax.random.PRNGKey(0)}, x, train=False
            )
            params = variables["params"]
            batch_stats = variables.get("batch_stats", {})
            tx = get_optimizer("adamw", 1e-4)
            criterion = get_criterion("cross_entropy")
            state = TrainState(
                step=jnp.zeros((), jnp.int32), params=params,
                opt_state=tx.init(params), batch_stats=batch_stats,
                rng=jax.random.PRNGKey(1),
            )
            has_bs = bool(batch_stats)

            @jax.jit
            def step(state, x, y):
                def loss_fn(p):
                    if has_bs:
                        out, mut = model.apply(
                            {"params": p, "batch_stats": state.batch_stats},
                            x, train=True, mutable=["batch_stats"],
                        )
                        return criterion(out, y), mut["batch_stats"]
                    out = model.apply({"params": p}, x, train=True)
                    return criterion(out, y), state.batch_stats

                (loss, new_bs), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(state.params)
                updates, opt_state = tx.update(
                    grads, state.opt_state, state.params
                )
                return (
                    state.replace(
                        step=state.step + 1,
                        params=optax.apply_updates(state.params, updates),
                        opt_state=opt_state,
                        batch_stats=new_bs,
                    ),
                    loss,
                )

            rate, _ = _steady_state_rate(
                step, state, [(x, y)], warmup=3, iters=20
            )
            rows.append((name, shape, rate * shape[0]))
        except Exception as e:  # keep the headline metric robust
            rows.append((name, shape, f"FAILED: {type(e).__name__}: {e}"))
    for name, shape, rate in rows:
        if isinstance(rate, float):
            print(f"# {name} {shape}: {rate:,.1f} samples/s")
        else:
            print(f"# {name} {shape}: {rate}")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--extended", action="store_true",
                        help="also bench the north-star model zoo")
    parser.add_argument("--batch_size", type=int, default=32)
    args = parser.parse_args()
    if args.extended:
        bench_extended()
    samples_per_sec = bench_parity(args.batch_size)
    print(
        json.dumps(
            {
                "metric": "train_samples_per_sec (MLModel/CIFAR-10, bs=32, full train step)",
                "value": round(samples_per_sec, 1),
                "unit": "samples/s",
                "vs_baseline": round(samples_per_sec / BASELINE_SAMPLES_PER_SEC, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
