"""Text generation with the KV-cached decode stack.

No analog in the reference (SURVEY.md §1: "no serving layer").  Shows
every decoding mode on one model: greedy, temperature/top-k/top-p
sampling, EOS early-stop, ragged prompts (length-bucketed), and beam
search — all through compiled static-shape programs (one prefill + one
lax.scan per shape; repeat calls hit the program cache).  Works with any
causal LM in the zoo; the llama family decodes through a
grouped-query-attention cache that stores only num_kv_heads-wide K/V.

    python examples/08_generation.py                 # gpt2_tiny, CPU-friendly
    MODEL=llama_tiny python examples/08_generation.py
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)
import os


import jax
import jax.numpy as jnp
import numpy as np

from ml_trainer_tpu.generate import beam_search, generate, generate_ragged
from ml_trainer_tpu.models import get_model

MODEL = os.environ.get("MODEL", "gpt2_tiny")

model = get_model(MODEL)
rng = np.random.default_rng(0)
prompt = jnp.asarray(
    rng.integers(1, model.vocab_size, size=(2, 6)), jnp.int32
)
# Random weights — the point here is the decode machinery, not prose.
variables = model.init({"params": jax.random.PRNGKey(0)}, prompt, train=False)

greedy = generate(model, variables, prompt, max_new_tokens=8)
print(f"greedy          {greedy.shape}: {np.asarray(greedy[0])}")

sampled = generate(
    model, variables, prompt, max_new_tokens=8,
    temperature=0.8, top_k=50, top_p=0.95, rng=jax.random.PRNGKey(7),
)
print(f"top-k/top-p     {sampled.shape}: {np.asarray(sampled[0])}")

stopped = generate(
    model, variables, prompt, max_new_tokens=8,
    eos_token_id=3, pad_token_id=0,
)
print(f"eos-stopped     {stopped.shape}: {np.asarray(stopped[0])}")

ragged = generate_ragged(
    model, variables,
    [np.array([5, 6]), np.array([7, 8, 9, 10, 11])],
    max_new_tokens=4, temperature=0.7,
)
print(f"ragged lens     {[len(r) for r in ragged]}")

beams = beam_search(model, variables, prompt, max_new_tokens=6, num_beams=4)
print(f"beam search     {beams.shape}: {np.asarray(beams[0])}")
