"""Distributed data-parallel training — the
02_ML_Training_SageMaker_distributed.ipynb flow, TPU-native.

Where the reference provisions SageMaker GPU instances and launches main.py
under SMDDP (02 nb cells 4-7), the TPU path is one command on each TPU VM
host — ``jax.distributed`` auto-detects multi-host TPU environments, and
the mesh spans every chip in the slice:

    python examples/02_distributed_training.py          # every host

Parallelism strategy is configurable the way the estimator's distribution
dict never was: pure DP by default; set TP=2 (env var) for a dp×tp mesh
with Megatron sharding rules.

To rehearse without TPU hardware (the local_gpu/gloo analog, SURVEY.md §4):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/02_distributed_training.py
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)
import os


from ml_trainer_tpu import Trainer
from ml_trainer_tpu.data import SyntheticCIFAR10
from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.parallel import rules_for
from ml_trainer_tpu.utils.functions import custom_pre_process_function

MODEL_DIR = os.environ.get("MODEL_DIR", "model_output_distributed")
TP = int(os.environ.get("TP", "1"))
MODEL = os.environ.get("MODEL", "resnet18")


def main():
    transform = custom_pre_process_function()
    n = int(os.environ.get("SYNTH_SIZE", "4096"))
    datasets = (
        SyntheticCIFAR10(size=n, transform=transform),
        SyntheticCIFAR10(size=max(n // 8, 64), transform=transform, seed=1),
    )
    # The reference's hyperparameters dict (02 nb cell-4), same keys.
    config = {
        "seed": 32,
        "optimizer": "sgd",
        "momentum": 0.9,
        "lr": 0.01,
        "criterion": "cross_entropy",
        "metric": "accuracy",
        "pred_function": "softmax",
        "model_dir": MODEL_DIR,
        "backend": "smddp",  # alias accepted; maps to the TPU mesh backend
    }
    mesh_shape = None
    sharding_rules = None
    if TP > 1:
        import jax

        mesh_shape = {"data": jax.device_count() // TP, "tensor": TP}
        sharding_rules = rules_for(MODEL, "tp")
    trainer = Trainer(
        get_model(MODEL),
        datasets=datasets,
        epochs=int(os.environ.get("EPOCHS", "2")),
        batch_size=int(os.environ.get("BATCH_SIZE", "256")),
        is_parallel=True,
        save_history=True,
        mesh_shape=mesh_shape,
        sharding_rules=sharding_rules,
        **config,
    )
    trainer.fit(resume=os.environ.get("RESUME") == "1")


if __name__ == "__main__":
    main()
