"""Local single-device training — the 01_ML_Training_local.ipynb flow.

Cell-for-cell equivalent of the reference notebook (build datasets + config
→ Trainer(epochs=6, batch_size=32) → fit → save/load/plot history →
load_model → test), driving the TPU instead of CPU/GPU.  Uses real CIFAR-10
when the pickle batches are on disk, synthetic data otherwise.
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)
import os


from ml_trainer_tpu import (
    MLModel,
    Loader,
    Trainer,
    load_history,
    load_model,
    plot_history,
)
from ml_trainer_tpu.data import CIFAR10, SyntheticCIFAR10
from ml_trainer_tpu.utils.functions import custom_pre_process_function

DATA_DIR = os.environ.get("DATA_DIR", "data")
MODEL_DIR = os.environ.get("MODEL_DIR", "model_output")


def build_datasets():
    transform = custom_pre_process_function()
    try:
        return (
            CIFAR10(DATA_DIR, train=True, transform=transform),
            CIFAR10(DATA_DIR, train=False, transform=transform),
        )
    except FileNotFoundError:
        print("CIFAR-10 not on disk; using synthetic data")
        return (
            SyntheticCIFAR10(size=2048, transform=transform),
            SyntheticCIFAR10(size=512, transform=transform, seed=1),
        )


def main():
    import json
    import time

    datasets = build_datasets()
    # The reference notebook's config dict (01 nb cell-8).
    config = {
        "seed": 32,
        "scheduler": "CosineAnnealingWarmRestarts",
        "optimizer": "sgd",
        "momentum": 0.9,
        "weight_decay": 0.0,
        "lr": 0.001,
        "criterion": "cross_entropy",
        "metric": "accuracy",
        "pred_function": "softmax",
        "model_dir": MODEL_DIR,
    }
    trainer = Trainer(
        MLModel(), datasets=datasets, epochs=6, batch_size=32,
        save_history=True, **config,
    )
    t0 = time.perf_counter()
    trainer.fit()
    fit_secs = time.perf_counter() - t0

    history = load_history(MODEL_DIR)
    print({k: v[-1] if isinstance(v, list) else v for k, v in history.items()})
    if os.environ.get("PLOT"):
        plot_history(history)

    loaded = load_model(MLModel(), MODEL_DIR)
    test_loader = Loader(datasets[1], batch_size=32, shuffle=True)
    test_loss, test_acc = trainer.test(loaded, test_loader)
    print(f"test loss {test_loss:.4f}  accuracy {test_acc:.4f}")

    # Golden-run capture (the analog of the reference's committed notebook
    # outputs, 01 nb cell-12/16): history + test metrics + throughput, used
    # by tests/test_golden.py as the regression baseline.
    golden_out = os.environ.get("GOLDEN_OUT")
    if golden_out:
        import jax

        n_train = len(datasets[0]) * trainer.epochs
        record = {
            "backend": jax.default_backend(),
            "synthetic": type(datasets[0]).__name__ == "SyntheticCIFAR10",
            "train_size": len(datasets[0]),
            "epochs": trainer.epochs,
            "history": history,
            "test_loss": float(test_loss),
            "test_accuracy": float(test_acc),
            "fit_wall_secs": round(fit_secs, 2),
            "train_samples_per_sec_incl_compile": round(n_train / fit_secs, 1),
        }
        with open(golden_out, "w") as f:
            json.dump(record, f, indent=1, default=float)
        print(f"golden record -> {golden_out}")


if __name__ == "__main__":
    main()
