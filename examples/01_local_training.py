"""Local single-device training — the 01_ML_Training_local.ipynb flow.

Cell-for-cell equivalent of the reference notebook (build datasets + config
→ Trainer(epochs=6, batch_size=32) → fit → save/load/plot history →
load_model → test), driving the TPU instead of CPU/GPU.  Uses real CIFAR-10
when the pickle batches are on disk, synthetic data otherwise.
"""

import os

from ml_trainer_tpu import (
    MLModel,
    Loader,
    Trainer,
    load_history,
    load_model,
    plot_history,
)
from ml_trainer_tpu.data import CIFAR10, SyntheticCIFAR10
from ml_trainer_tpu.utils.functions import custom_pre_process_function

DATA_DIR = os.environ.get("DATA_DIR", "data")
MODEL_DIR = os.environ.get("MODEL_DIR", "model_output")


def build_datasets():
    transform = custom_pre_process_function()
    try:
        return (
            CIFAR10(DATA_DIR, train=True, transform=transform),
            CIFAR10(DATA_DIR, train=False, transform=transform),
        )
    except FileNotFoundError:
        print("CIFAR-10 not on disk; using synthetic data")
        return (
            SyntheticCIFAR10(size=2048, transform=transform),
            SyntheticCIFAR10(size=512, transform=transform, seed=1),
        )


def main():
    datasets = build_datasets()
    # The reference notebook's config dict (01 nb cell-8).
    config = {
        "seed": 32,
        "scheduler": "CosineAnnealingWarmRestarts",
        "optimizer": "sgd",
        "momentum": 0.9,
        "weight_decay": 0.0,
        "lr": 0.001,
        "criterion": "cross_entropy",
        "metric": "accuracy",
        "pred_function": "softmax",
        "model_dir": MODEL_DIR,
    }
    trainer = Trainer(
        MLModel(), datasets=datasets, epochs=6, batch_size=32,
        save_history=True, **config,
    )
    trainer.fit()

    history = load_history(MODEL_DIR)
    print({k: v[-1] if isinstance(v, list) else v for k, v in history.items()})
    if os.environ.get("PLOT"):
        plot_history(history)

    loaded = load_model(MLModel(), MODEL_DIR)
    test_loader = Loader(datasets[1], batch_size=32, shuffle=True)
    test_loss, test_acc = trainer.test(loaded, test_loader)
    print(f"test loss {test_loss:.4f}  accuracy {test_acc:.4f}")


if __name__ == "__main__":
    main()
