"""Long-context training — the full sequence-length stack on one command
line.  No analog in the reference (it has no attention at all, SURVEY.md
§5); this is the extension surface the TPU build treats as first-class:

* sequence parallelism over the mesh (`SP` devices): ring attention
  (`ATTN=ring`, K/V blocks rotate over ICI ppermute) or Ulysses
  (`ATTN=ulysses`, head/sequence all-to-all) — the hidden states are
  sharded over the sequence axis end-to-end, so per-device activation
  memory scales 1/SP with the context;
* per-block rematerialization (`REMAT=1`, optionally
  `REMAT_POLICY=dots`) — backward activation memory O(1) blocks;
* chunked LM loss (`LOSS_CHUNK=n`) — the [B, S, V] logits tensor is
  never materialized (GPT-2 124M at 8x1024 would hold ~0.8 GB of it).

    python examples/06_long_context.py                   # CPU-mesh smoke
    SEQ_LEN=2048 SP=4 ATTN=ring REMAT=1 REMAT_POLICY=dots LOSS_CHUNK=128 \
        python examples/06_long_context.py               # the long config

All three levers are math-preserving: the trajectory equals the dense
single-device run (tests/test_parallel.py::test_long_context_stack_composes).
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)
import os


from ml_trainer_tpu import Trainer
from ml_trainer_tpu.data import SyntheticTokens
from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.parallel import create_mesh

SEQ_LEN = int(os.environ.get("SEQ_LEN", "128"))
SP = int(os.environ.get("SP", "4"))
DP = int(os.environ.get("DP", "0"))  # 0 -> whatever SP leaves (02/04 style)
BATCH = int(os.environ.get("BATCH", "8"))
EPOCHS = int(os.environ.get("EPOCHS", "2"))
ATTN = os.environ.get("ATTN", "ring")  # ring | ulysses
MODEL_DIR = os.environ.get("MODEL_DIR", "model_output_longctx")


def main():
    n = int(os.environ.get("SYNTH_SIZE", "128"))
    vocab = int(os.environ.get("VOCAB", "1024"))
    datasets = (
        SyntheticTokens(size=n, seq_len=SEQ_LEN, vocab_size=vocab),
        SyntheticTokens(size=max(n // 4, 16), seq_len=SEQ_LEN,
                        vocab_size=vocab, seed=1),
    )
    import jax

    n_dev = jax.device_count()
    dp = DP or max(n_dev // SP, 1)
    if SP > 1 and dp * SP == n_dev:
        mesh_shape = {"data": dp, "sequence": SP}
        attn = ATTN
        mesh = create_mesh(mesh_shape)
    else:
        # The sequence axis doesn't fit this machine (e.g. a single chip,
        # or DP*SP != device count): run the same model dense — the remat
        # and loss-chunk levers below still apply.
        mesh_shape = {"data": n_dev}
        attn = "auto"
        mesh = None
        print(f"# {dp}x{SP} mesh != {n_dev} devices; "
              f"running dense on {mesh_shape}")
    loss_chunk = int(os.environ.get("LOSS_CHUNK", "0"))
    model = get_model(
        "gpt2_tiny", vocab_size=vocab, max_len=SEQ_LEN,
        attention_impl=attn, mesh=mesh,
        remat=os.environ.get("REMAT") == "1",
        remat_policy=os.environ.get("REMAT_POLICY", "none"),
        loss_chunk=loss_chunk,
    )
    trainer = Trainer(
        model,
        datasets=datasets,
        epochs=EPOCHS,
        batch_size=BATCH,
        is_parallel=True,
        save_history=True,
        mesh_shape=mesh_shape,
        optimizer="adamw",
        lr=float(os.environ.get("LR", "3e-4")),
        scheduler="WarmupCosine",
        metric=None,  # self-loss model when LOSS_CHUNK is set
        model_dir=MODEL_DIR,
    )
    trainer.fit()
    print({
        "train_loss": trainer.train_losses[-1],
        "val_loss": trainer.val_losses[-1],
        "seq_len": SEQ_LEN, "sp": SP, "attn": attn,
        "loss_chunk": loss_chunk,
    })


if __name__ == "__main__":
    main()
