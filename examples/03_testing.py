"""Inference-only evaluation — the 03_ML_Testing.ipynb flow.

Build a test loader → load the saved model → dataset-less Trainer (the
"Testing only available" path, ref: src/trainer.py:66-71) →
``trainer.test`` returning (loss, metric).  Also demonstrates loading a
reference torch ``model.pth`` checkpoint (the ``module.``-prefix-tolerant
import, ref: src/utils/utils.py:15-28).
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)
import os
import sys

from ml_trainer_tpu import MLModel, Loader, Trainer, load_model
from ml_trainer_tpu.data import CIFAR10, SyntheticCIFAR10
from ml_trainer_tpu.utils.functions import custom_pre_process_function

MODEL_DIR = os.environ.get("MODEL_DIR", "model_output")
DATA_DIR = os.environ.get("DATA_DIR", "data")


def main():
    transform = custom_pre_process_function()
    try:
        val_set = CIFAR10(DATA_DIR, train=False, transform=transform)
    except FileNotFoundError:
        val_set = SyntheticCIFAR10(size=512, transform=transform, seed=1)
    test_loader = Loader(val_set, batch_size=32, shuffle=True)

    checkpoint = sys.argv[1] if len(sys.argv) > 1 else MODEL_DIR
    model = load_model(MLModel(), checkpoint)  # .msgpack dir or torch .pth

    trainer = Trainer(MLModel())  # no datasets: inference-only trainer
    test_loss, test_metric = trainer.test(model, test_loader)
    print(f"loss {test_loss:.4f}  accuracy {test_metric:.4f}")


if __name__ == "__main__":
    main()
