"""Beyond-RAM streaming + preemption-elastic training, one flow.

No analog in the reference (its datasets are in-memory torchvision
objects and a failed job is simply lost — SURVEY.md §5); these are the
two capabilities that make ImageNet-class training on preemptible TPUs
practical:

1. **Sharded on-disk dataset** — images live in memory-mapped per-shard
   ``.npy`` files (`write_sharded_dataset` / `ingest_image_folder` for
   raw JPEG trees); both the Python Loader and the C++ native worker
   gather straight from the mapped pages, so host RAM never holds the
   dataset.
2. **Sharded checkpoints + elastic resume** — every process writes only
   its addressable shards each epoch; if the job is preempted and comes
   back on a DIFFERENT device count, ``fit(resume=True)`` stitches the
   state onto the new mesh and the trajectory continues
   (tests/test_elastic.py proves equality with an uninterrupted run).

    python examples/07_streaming_and_elastic.py          # CPU-mesh smoke
    EPOCHS=90 python examples/07_streaming_and_elastic.py  # real run shape
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)
import os

import tempfile

import numpy as np

from ml_trainer_tpu import MLModel, Trainer
from ml_trainer_tpu.data import ShardedImageDataset, write_sharded_dataset
from ml_trainer_tpu.utils.functions import custom_pre_process_function

EPOCHS = int(os.environ.get("EPOCHS", "2"))
DATA_DIR = os.environ.get("DATA_DIR", "")  # preexisting sharded dataset
MODEL_DIR = os.environ.get("MODEL_DIR", os.path.join(tempfile.gettempdir(),
                                                     "streaming_run"))

if DATA_DIR:
    train_dir = os.path.join(DATA_DIR, "train")
    val_dir = os.path.join(DATA_DIR, "val")
else:
    # Demo: write a synthetic sharded dataset (streaming writer — peak
    # RAM is one shard).  For a real JPEG tree use
    # ``ingest_image_folder(src, dst, size=(224, 224))`` instead.
    root = tempfile.mkdtemp(prefix="sharded_demo_")
    rng = np.random.default_rng(0)
    train_dir = write_sharded_dataset(
        os.path.join(root, "train"),
        ((rng.integers(0, 256, (256, 32, 32, 3), dtype=np.uint8),
          rng.integers(0, 10, (256,)).astype(np.int32))
         for _ in range(4)),
        samples_per_shard=300,
    )
    val_dir = write_sharded_dataset(
        os.path.join(root, "val"),
        [(rng.integers(0, 256, (128, 32, 32, 3), dtype=np.uint8),
          rng.integers(0, 10, (128,)).astype(np.int32))],
        samples_per_shard=300,
    )

transform = custom_pre_process_function()
datasets = (
    ShardedImageDataset(train_dir, transform),
    ShardedImageDataset(val_dir, transform),
)

trainer = Trainer(
    MLModel(),
    datasets=datasets,
    epochs=EPOCHS,
    batch_size=64,
    model_dir=MODEL_DIR,
    is_parallel=True,
    metric="accuracy",
    optimizer="adam",
    lr=0.001,
    # Per-host sharded checkpoints; with ZeRO-1 the moments are written
    # as the shards they live as.  On preemption, relaunch with
    # resume=True on WHATEVER slice comes back.
    shard_opt_state=True,
    sharded_checkpoint=True,
)
trainer.fit(resume=os.environ.get("RESUME") == "1")
print(f"final train loss: {trainer.train_losses[-1]:.4f}  "
      f"(checkpoints in {MODEL_DIR}/checkpoints)")
