"""BERT sequence-classification fine-tune — the SST-2 north-star config
(BASELINE.json configs[2]: BERT-base SST-2 fine-tune).

Uses real GLUE SST-2 TSVs when present (DATA_DIR/train.tsv + dev.tsv),
otherwise a tiny synthetic sentiment set through the same tokenize →
TokenizedDataset → Trainer path.  Tokenization is the REAL in-tree
WordPiece tokenizer (the repo's fixture vocab.txt by default — drop
the published BERT vocab.txt into data/tokenizer/ or point
ML_TRAINER_TPU_VOCAB_DIR at it to upgrade); TOKENIZER=hash opts back
into the deterministic hash stand-in.

    python examples/05_bert_finetune.py                       # tiny, smoke
    MODEL=bert_base DATA_DIR=data/sst2 EPOCHS=3 BATCH=32 \
        python examples/05_bert_finetune.py                   # the real one
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)
import os


from ml_trainer_tpu import Trainer
from ml_trainer_tpu.data.text import TokenizedDataset, load_sst2_tsv
from ml_trainer_tpu.models import get_model

MODEL = os.environ.get("MODEL", "bert_tiny")
DATA_DIR = os.environ.get("DATA_DIR", "data/sst2")
MODEL_DIR = os.environ.get("MODEL_DIR", "model_output_bert")
MAX_LEN = int(os.environ.get("MAX_LEN", "64"))

SYNTH = [
    ("a joyous, generous film that deserves every award", 1),
    ("warm and funny from the first scene to the last", 1),
    ("an absolute delight, sharp writing and great heart", 1),
    ("the best surprise of the year, simply wonderful", 1),
    ("tedious, joyless and far too long", 0),
    ("a dull mess with nothing to say", 0),
    ("painfully bad acting sinks every scene", 0),
    ("a waste of a talented cast, avoid it", 0),
] * 16


# WordPiece is the BERT-shaped encoding; 'hash' reverts to the stand-in.
TOKENIZER = os.environ.get("TOKENIZER", "wordpiece")


def build_datasets(vocab_size):
    try:
        return (
            load_sst2_tsv(os.path.join(DATA_DIR, "train.tsv"),
                          max_len=MAX_LEN, vocab_size=vocab_size,
                          tokenizer=TOKENIZER),
            load_sst2_tsv(os.path.join(DATA_DIR, "dev.tsv"),
                          max_len=MAX_LEN, vocab_size=vocab_size,
                          tokenizer=TOKENIZER),
        )
    except (FileNotFoundError, OSError):
        print("SST-2 TSVs not on disk; using the synthetic sentiment set")
        texts, labels = zip(*SYNTH)
        n = len(texts) * 3 // 4
        mk = lambda t, l: TokenizedDataset.from_texts(  # noqa: E731
            t, l, max_len=MAX_LEN, vocab_size=vocab_size,
            tokenizer=TOKENIZER,
        )
        return mk(texts[:n], labels[:n]), mk(texts[n:], labels[n:])


def main():
    # right_padded: TokenizedDataset pads on the right by construction, so
    # the padding masks compress to kv_lens and run the fused flash kernel.
    model_kw = {"num_classes": 2, "right_padded": True}
    vocab_size = 30522
    if MODEL == "bert_tiny":
        vocab_size = 2048
        model_kw.update(vocab_size=vocab_size, max_len=MAX_LEN)
    datasets = build_datasets(vocab_size)
    trainer = Trainer(
        get_model(MODEL, **model_kw),
        datasets=datasets,
        epochs=int(os.environ.get("EPOCHS", "3")),
        batch_size=int(os.environ.get("BATCH", "16")),
        save_history=True,
        optimizer="adamw",
        lr=float(os.environ.get("LR", "2e-4")),
        weight_decay=0.01,
        criterion="cross_entropy",
        metric="accuracy",
        pred_function="softmax",
        model_dir=MODEL_DIR,
    )
    trainer.fit()
    print({k: (v[-1] if isinstance(v, list) else v)
           for k, v in trainer.history.items()})


if __name__ == "__main__":
    main()
