"""Shared example bootstrap: put the repo root on sys.path.

``python examples/<name>.py`` puts only the script's own directory on
sys.path, so ``ml_trainer_tpu`` would not resolve; every example does
``import _bootstrap`` (this module lives next to them, hence importable
in exactly that situation, and under runpy.run_path too) and gets the
repo root inserted once.
"""

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)
