"""GPT-2 pretraining — the north-star LM config (BASELINE.json configs[4]:
gradient accumulation + checkpoint save/restore), with every memory/perf
lever of the framework on one command line.

    python examples/04_gpt2_pretrain.py                  # tiny model, smoke
    MODEL=gpt2 SEQ_LEN=1024 BATCH=8 ACCUM=4 REMAT=1 \
        python examples/04_gpt2_pretrain.py              # the real config
    RESUME=1 python examples/04_gpt2_pretrain.py         # continue from ckpt

Levers (env vars): ACCUM (microbatches per update, compiled scan), REMAT
(jax.checkpoint per block), ZERO1 (optimizer-state sharding over data),
K (steps per dispatch), TP (tensor-parallel degree over a dp*tp mesh).

Data: a synthetic text corpus tokenized by the REAL in-tree byte-level
BPE tokenizer (the repo's fixture vocab by default; drop the published
GPT-2 vocab.json+merges.txt into data/tokenizer/ or set
ML_TRAINER_TPU_VOCAB_DIR to upgrade) packed into next-token blocks —
the real GPT-2 data path.  TOKENIZER=synth reverts to raw synthetic
token ids.
"""

import _bootstrap  # noqa: F401  (repo root onto sys.path)
import os


import jax

from ml_trainer_tpu import Trainer
from ml_trainer_tpu.data import SyntheticTokens
from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.parallel import rules_for

MODEL = os.environ.get("MODEL", "gpt2_tiny")
SEQ_LEN = int(os.environ.get("SEQ_LEN", "128"))
BATCH = int(os.environ.get("BATCH", "16"))
EPOCHS = int(os.environ.get("EPOCHS", "2"))
ACCUM = int(os.environ.get("ACCUM", "2"))
TP = int(os.environ.get("TP", "1"))
MODEL_DIR = os.environ.get("MODEL_DIR", "model_output_gpt2")
TOKENIZER = os.environ.get("TOKENIZER", "bpe")  # 'synth': raw token ids


def build_datasets(n, n_val, vocab):
    """(vocab_size, (train, val)) — real-BPE packed blocks by default,
    raw synthetic token ids with TOKENIZER=synth."""
    from ml_trainer_tpu.data.tokenizers import (
        load_tokenizer,
        resolve_vocab_dir,
    )

    tok = None if TOKENIZER == "synth" else load_tokenizer(
        resolve_vocab_dir()
    )
    if tok is None:
        # Causal-LM pairs: labels are the inputs shifted left
        # (SyntheticTokens emits them already shifted when num_classes
        # is None).
        return vocab, (
            SyntheticTokens(size=n, seq_len=SEQ_LEN, vocab_size=vocab),
            SyntheticTokens(size=n_val, seq_len=SEQ_LEN,
                            vocab_size=vocab, seed=1),
        )
    import numpy as np

    from ml_trainer_tpu.data import PackedLMDataset

    vocab = max(vocab, tok.vocab_size)
    need = (n + n_val) * SEQ_LEN + 2
    stream = []
    i = 0
    while len(stream) < need:
        stream.extend(tok.encode(
            f"training step {i}: the tiny gpt model fits the mesh "
            "and the loss goes down. "
        ))
        i += 1
    stream = np.asarray(stream[:need], np.int32)
    split = n * SEQ_LEN + 1
    return vocab, (
        PackedLMDataset(stream[:split], SEQ_LEN),
        PackedLMDataset(stream[split - 1:], SEQ_LEN),
    )


def main():
    n = int(os.environ.get("SYNTH_SIZE", "512"))
    vocab, datasets = build_datasets(
        n, max(n // 8, 32), int(os.environ.get("VOCAB", "1024"))
    )
    model_kw = dict(remat=os.environ.get("REMAT") == "1")
    if MODEL == "gpt2_tiny":
        model_kw.update(vocab_size=vocab, max_len=SEQ_LEN)
    mesh_shape = None
    sharding_rules = None
    if TP > 1:
        mesh_shape = {"data": jax.device_count() // TP, "tensor": TP}
        sharding_rules = rules_for("gpt2", "tp")
    trainer = Trainer(
        get_model(MODEL, **model_kw),
        datasets=datasets,
        epochs=EPOCHS,
        batch_size=BATCH,
        is_parallel=os.environ.get("PARALLEL") == "1",
        save_history=True,
        grad_accum_steps=ACCUM,
        steps_per_execution=int(os.environ.get("K", "1")),
        shard_opt_state=os.environ.get("ZERO1") == "1",
        mesh_shape=mesh_shape,
        sharding_rules=sharding_rules,
        optimizer="adamw",
        lr=float(os.environ.get("LR", "3e-4")),
        weight_decay=0.01,
        criterion="cross_entropy",
        scheduler="CosineAnnealingWarmRestarts",
        model_dir=MODEL_DIR,
    )
    trainer.fit(resume=os.environ.get("RESUME") == "1")
    print({k: (v[-1] if isinstance(v, list) else v)
           for k, v in trainer.history.items()})

    # Decode a short continuation with the trained weights — KV-cached,
    # one compiled program (ml_trainer_tpu.generate).
    import jax.numpy as jnp

    from ml_trainer_tpu import generate

    prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    out = generate(
        trainer.model, {"params": trainer.state.params},
        prompt, max_new_tokens=16, temperature=0.8,
        rng=jax.random.PRNGKey(0),
    )
    print("sampled continuation:", out[0, prompt.shape[1]:].tolist())


if __name__ == "__main__":
    main()
