"""Launcher / CLI entry point.

Plays the role of the reference's SageMaker container entry
(ref: main.py:9-84): parse flags, build CIFAR-10 datasets (optionally with
the custom preprocess pipeline), construct the Trainer and run ``fit()``.
TPU-native differences:

* ``--backend tpu`` replaces the SageMaker estimator/SMDDP path — on a TPU
  VM (single- or multi-host) the same command runs everywhere; multi-host
  rendezvous happens through ``jax.distributed`` env auto-detection instead
  of SageMaker's MPI-style env (ref: main.py:80-83).
* The SageMaker env vars (SM_MODEL_DIR, SM_CHANNEL_TRAIN) are still honored
  as defaults when present, so an estimator-style launch keeps working.
* ``--batch_size`` / ``--epochs`` are honored.  The reference parses them
  but hardcodes 32/250 (ref: main.py:44) — a bug we deliberately fix.
* ``--custom_function`` is a real boolean flag.  The reference declares it
  ``type=bool`` which makes any non-empty string truthy (ref: main.py:74-75)
  — fixed.
* ``--model`` selects from the model zoo (the reference hardcodes MLModel,
  ref: main.py:30); ``--synthetic`` substitutes deterministic synthetic data
  for environments without the dataset on disk.
"""

from __future__ import annotations

import argparse
import os

from ml_trainer_tpu import Trainer
from ml_trainer_tpu.data.datasets import CIFAR10, SyntheticCIFAR10
from ml_trainer_tpu.models import get_model
from ml_trainer_tpu.utils.functions import custom_pre_process_function


def build_datasets(args):
    if args.synthetic_tokens:
        # LM path: next-token prediction over synthetic token streams —
        # makes the GPT-2 family runnable from this entry point (the
        # image path below matches the reference's CIFAR-only CLI).
        if args.custom_function:
            raise SystemExit(
                "--custom_function is the CIFAR augmentation pipeline; it "
                "does not apply to --synthetic_tokens"
            )
        if "bert" in args.model:
            raise SystemExit(
                "--synthetic_tokens drives next-token LM training; the "
                "bert models are sequence classifiers — fine-tune them on "
                "a TokenizedDataset instead (examples/05_bert_finetune.py)"
            )
        from ml_trainer_tpu.data import SyntheticTokens

        return (
            SyntheticTokens(size=args.synthetic_train_size,
                            seq_len=args.seq_len,
                            vocab_size=args.vocab_size),
            SyntheticTokens(size=args.synthetic_val_size,
                            seq_len=args.seq_len,
                            vocab_size=args.vocab_size, seed=1),
        )
    transform = custom_pre_process_function() if args.custom_function else None
    if args.synthetic:
        return (
            SyntheticCIFAR10(size=args.synthetic_train_size, transform=transform),
            SyntheticCIFAR10(size=args.synthetic_val_size, transform=transform, seed=1),
        )
    return (
        CIFAR10(root=args.data_dir, train=True, transform=transform),
        CIFAR10(root=args.data_dir, train=False, transform=transform),
    )


def main(args) -> None:
    datasets = build_datasets(args)
    model_kw = {}
    if args.dtype:
        import jax.numpy as jnp

        model_kw["dtype"] = {
            "float32": jnp.float32, "bfloat16": jnp.bfloat16,
            "bf16": jnp.bfloat16, "f32": jnp.float32,
        }[args.dtype]
    if args.remat_policy != "none" and not args.remat:
        raise SystemExit(
            "--remat_policy only applies with --remat (it controls what "
            "the per-block checkpoint may keep)"
        )
    if args.remat:
        model_kw["remat"] = True
        if args.remat_policy != "none":
            model_kw["remat_policy"] = args.remat_policy
    if args.synthetic_tokens:
        # The model's vocabulary/context must cover the synthetic stream.
        model_kw["vocab_size"] = args.vocab_size
        model_kw["max_len"] = args.seq_len
    if args.loss_chunk:
        model_kw["loss_chunk"] = args.loss_chunk
    if args.moe_top_k != 1:
        model_kw["moe_top_k"] = args.moe_top_k
    if model_kw:
        try:
            model = get_model(args.model, **model_kw)
        except TypeError as e:
            raise SystemExit(
                f"model {args.model!r} does not accept {sorted(model_kw)} "
                f"(--dtype applies to the transformer/resnet families, "
                f"--remat/--remat_policy to the transformer families, "
                f"--loss_chunk to the "
                f"GPT-2 family, --moe_top_k to the MoE variants; "
                f"--synthetic_tokens itself injects vocab_size/max_len, so "
                f"it only pairs with the token models): {e}"
            )
    else:
        model = get_model(args.model)
    config = {
        "seed": args.seed,
        "scheduler": args.scheduler,
        "optimizer": args.optimizer,
        "momentum": args.momentum,
        "weight_decay": args.weight_decay,
        "lr": args.lr,
        "criterion": args.criterion,
        "pred_function": args.pred_function,
        "metric": args.metric,
        "model_dir": args.model_dir,
        "backend": args.backend,
    }
    trainer = Trainer(
        model,
        datasets=datasets,
        epochs=args.epochs,
        batch_size=args.batch_size,
        is_parallel=args.is_parallel,
        save_history=True,
        steps_per_execution=args.steps_per_execution,
        grad_accum_steps=args.grad_accum_steps,
        shard_opt_state=args.shard_opt_state,
        grad_clip_norm=args.grad_clip_norm,
        ema_decay=args.ema_decay,
        early_stop_patience=args.early_stop_patience,
        save_best=args.save_best,
        decay_exclude_bias_norm=args.decay_exclude_bias_norm,
        label_smoothing=args.label_smoothing,
        **config,
    )
    if args.profile:
        from ml_trainer_tpu.utils.profiler import trace

        with trace(args.profile):
            trainer.fit(resume=args.resume)
        print(f"profiler trace -> {args.profile} (load in TensorBoard)")
    else:
        trainer.fit(resume=args.resume)


def parse_args(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    # Training flags — same names/defaults as ref: main.py:52-77.
    parser.add_argument("--batch_size", type=int, default=32,
                        help="global batch size for training (default: 32)")
    parser.add_argument("--epochs", type=int, default=10,
                        help="number of epochs to train (default: 10)")
    parser.add_argument("--optimizer", type=str, default="sgd",
                        help="optimizer for the update step (default: sgd)")
    parser.add_argument("--lr", type=float, default=0.001,
                        help="learning rate (default: 0.001)")
    parser.add_argument("--momentum", type=float, default=0.9,
                        help="optimizer momentum (default: 0.9)")
    parser.add_argument("--weight_decay", type=float, default=0.0,
                        help="optimizer weight decay (default: 0.0)")
    parser.add_argument("--seed", type=int, default=32,
                        help="random seed (default: 32)")
    parser.add_argument("--scheduler", type=str, default=None,
                        help="LR scheduler name (default: None)")
    parser.add_argument("--criterion", type=str, default="cross_entropy",
                        help="loss function (default: cross_entropy)")
    parser.add_argument("--metric", type=str, default=None,
                        help="evaluation metric (default: None)")
    parser.add_argument("--backend", type=str, default="tpu",
                        help="communication backend: tpu | cpu "
                             "(smddp/nccl/gloo accepted as aliases)")
    parser.add_argument("--custom_function", action="store_true",
                        help="apply the custom preprocess pipeline")
    parser.add_argument("--pred_function", type=str, default=None,
                        help="probability function for predictions")
    # TPU-native additions.
    parser.add_argument("--model", type=str, default="mlmodel",
                        help="model zoo name (default: mlmodel)")
    parser.add_argument("--is_parallel", action="store_true",
                        help="train data-parallel over the full device mesh")
    parser.add_argument("--resume", action="store_true",
                        help="resume from the latest full checkpoint")
    parser.add_argument("--synthetic", action="store_true",
                        help="use deterministic synthetic CIFAR-10 data")
    parser.add_argument("--synthetic_tokens", action="store_true",
                        help="use synthetic token streams (next-token "
                             "prediction) — the LM path for the "
                             "gpt2 family")
    parser.add_argument("--seq_len", type=int, default=128,
                        help="sequence length for --synthetic_tokens")
    parser.add_argument("--vocab_size", type=int, default=1024,
                        help="vocabulary size for --synthetic_tokens")
    parser.add_argument("--synthetic_train_size", type=int, default=2048)
    parser.add_argument("--synthetic_val_size", type=int, default=512)
    parser.add_argument("--dtype", type=str, default=None,
                        choices=["float32", "bfloat16", "bf16", "f32"],
                        help="model compute dtype (params stay f32); "
                             "bfloat16 is the MXU-native choice")
    parser.add_argument("--remat", action="store_true",
                        help="jax.checkpoint per transformer block "
                             "(activation memory O(depth) -> O(1) layers)")
    parser.add_argument("--remat_policy", type=str, default="none",
                        choices=["none", "dots"],
                        help="with --remat: what the checkpoint may keep "
                             "('dots' keeps matmul outputs — less "
                             "recompute for some memory back)")
    parser.add_argument("--loss_chunk", type=int, default=0,
                        help="GPT-2 family: compute the LM loss in "
                             "sequence chunks of this size inside the "
                             "forward — the [B,S,V] logits tensor is never "
                             "materialized (metric must be none)")
    parser.add_argument("--moe_top_k", type=int, default=1,
                        help="MoE variants: experts per token "
                             "(1 = Switch, 2 = GShard)")
    parser.add_argument("--profile", type=str, default=None,
                        help="directory for a jax.profiler trace of the "
                             "whole fit (TensorBoard-loadable)")
    parser.add_argument("--steps_per_execution", type=int, default=1,
                        help="optimizer steps per device dispatch "
                             "(lax.scan inside one compiled program; "
                             "trajectory identical, dispatch amortized)")
    parser.add_argument("--grad_accum_steps", type=int, default=1,
                        help="microbatches per optimizer update (compiled "
                        "scan — the GPT-2 large-batch lever)")
    parser.add_argument("--shard_opt_state", action="store_true",
                        help="ZeRO-1 placement: partition optimizer moments "
                        "over the data mesh axis")
    parser.add_argument("--grad_clip_norm", type=float, default=None,
                        help="clip gradients to this global L2 norm "
                             "before the optimizer update")
    parser.add_argument("--ema_decay", type=float, default=None,
                        help="keep an exponential moving average of the "
                             "params; eval/save then use the EMA weights")
    parser.add_argument("--early_stop_patience", type=int, default=None,
                        help="stop when validation loss has not improved "
                             "for this many epochs (counters live in "
                             "checkpoints, so --resume keeps counting)")
    parser.add_argument("--save_best", action="store_true",
                        help="also export weights to <model_dir>/best "
                             "whenever validation loss improves")
    parser.add_argument("--decay_exclude_bias_norm", action="store_true",
                        help="weight decay touches matrices only (skip "
                             "biases/LayerNorm — the transformer recipe)")
    parser.add_argument("--label_smoothing", type=float, default=0.0,
                        help="mix one-hot targets with the uniform "
                             "distribution at this weight (cross_entropy "
                             "only; the ViT/ResNet recipe)")
    # SageMaker-compatible env-backed paths (ref: main.py:80-83), with sane
    # defaults when the env vars are absent.
    parser.add_argument("--model_dir", type=str,
                        default=os.environ.get("SM_MODEL_DIR", "model_output"))
    parser.add_argument("--data_dir", type=str,
                        default=os.environ.get("SM_CHANNEL_TRAIN", "data"))
    return parser.parse_args(argv)


def cli(argv=None) -> None:
    """Console-script entry point (``ml-trainer-tpu`` after install)."""
    main(parse_args(argv))


if __name__ == "__main__":
    cli()
