"""Tokenized-text datasets — the BERT/GPT-2 north-star data path
(BASELINE.json configs[2], [4]: "tokenized src/dataloader.py path").

The reference has no text pipeline at all; this module provides:

* ``TokenizedDataset`` — padded [N, S] token ids (+ labels), an ArrayDataset
  so the Loader's fast batched-gather path applies;
* ``tokenize_texts`` — real tokenization by default: the IN-TREE
  byte-BPE/WordPiece tokenizers (data/tokenizers.py; the repo's fixture
  vocabs as zero-egress last resort) rank ahead of ``transformers``,
  and the deterministic hash stand-in is an explicit opt-in
  (``tokenizer='hash'``);
* ``load_sst2_tsv`` — the GLUE SST-2 on-disk format (sentence\\tlabel).
* ``PackedLMDataset`` — concatenate-and-chunk token stream for causal-LM
  pretraining (every token supervised, no padding waste).
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ml_trainer_tpu.data.datasets import ArrayDataset


def _stable_hash(word: str) -> int:
    """Process-independent word hash (builtin ``hash`` is salted per
    interpreter — it would tokenize the same text differently on every
    host/run)."""
    import hashlib

    return int.from_bytes(
        hashlib.md5(word.encode("utf-8")).digest()[:8], "little"
    )


# One-shot flag for the implicit data/tokenizer/ discovery warning.
_warned_implicit_vocab = False


def _hash_tokenize(text: str, vocab_size: int) -> List[int]:
    """Deterministic fallback tokenizer (whitespace + stable hash)."""
    return [
        (_stable_hash(w) % (vocab_size - 3)) + 3  # reserve 0=pad, 1=cls, 2=sep
        for w in text.lower().split()
    ]


def tokenize_texts(
    texts: Sequence[str],
    max_len: int = 128,
    tokenizer_name: Optional[str] = None,
    vocab_size: int = 30522,
    vocab_dir: Optional[str] = None,
    tokenizer: str = "auto",
) -> Tuple[np.ndarray, np.ndarray]:
    """Texts -> (input_ids [N, max_len], attention_mask [N, max_len]).

    Tokenizer preference order (``tokenizer='auto'``):

    1. the IN-TREE tokenizers (data/tokenizers.py) from ``vocab_dir`` —
       or ``$ML_TRAINER_TPU_VOCAB_DIR``, or ``data/tokenizer/``, or the
       repo's committed fixture vocabs as last resort
       (``vocab.json``+``merges.txt`` -> byte-level BPE; ``vocab.txt``
       -> WordPiece; ``tokenizer='bpe'``/``'wordpiece'`` tie-breaks a
       dir holding both).  Token ids then come from that vocab: build
       the model with the tokenizer's ``vocab_size``, not this
       function's ``vocab_size`` argument;
    2. ``transformers.AutoTokenizer`` when ``tokenizer_name`` is given
       and loadable (local files honored; no download attempted) —
       an explicit ``tokenizer_name`` also disables the fixture-vocab
       fallback in step 1, so it cannot be shadowed by defaults;
    3. the deterministic hash stand-in ONLY by explicit opt-in
       (``tokenizer='hash'``), bounded by ``vocab_size``, with
       BERT-style [CLS] ... [SEP] framing — or, with a loud warning, as
       the final fallback when nothing else is available.
    """
    if tokenizer not in ("auto", "bpe", "wordpiece", "hash"):
        raise ValueError(
            "tokenizer must be 'auto', 'bpe', 'wordpiece' or 'hash', "
            f"got {tokenizer!r}"
        )
    if tokenizer != "hash":
        from ml_trainer_tpu.data.tokenizers import (
            encode_batch,
            fixture_vocab_dir,
            load_tokenizer,
            resolve_vocab_dir,
        )

        implicit = vocab_dir is None and not os.environ.get(
            "ML_TRAINER_TPU_VOCAB_DIR"
        )
        resolved = resolve_vocab_dir(vocab_dir)
        if (
            tokenizer_name is not None
            and implicit
            and resolved == fixture_vocab_dir()
        ):
            # The caller named a transformers tokenizer and no real
            # vocab dir was configured: the fixture fallback must not
            # shadow the explicit request.
            resolved = ""
        prefer = tokenizer if tokenizer in ("bpe", "wordpiece") else None
        tok = (
            load_tokenizer(resolved, prefer=prefer)
            if resolved and os.path.isdir(resolved) else None
        )
        if tok is None and tokenizer in ("bpe", "wordpiece"):
            raise FileNotFoundError(
                f"tokenizer={tokenizer!r} requested but no vocab files "
                f"in {resolved!r}"
            )
        if (
            tok is not None
            and implicit
            and resolved == os.path.join("data", "tokenizer")
        ):
            # The mere presence of a CWD-relative data/tokenizer/
            # changes token ids for callers that never asked for it;
            # say so ONCE per process so the switch is visible, not
            # silent.  (The fixture fallback is the documented default
            # and does not warn.)
            global _warned_implicit_vocab
            if not _warned_implicit_vocab:
                _warned_implicit_vocab = True
                import warnings

                warnings.warn(
                    f"tokenize_texts discovered a vocab in {resolved!r} "
                    "(CWD-relative default) and will use it instead of "
                    "the fixture default; pass vocab_dir=... or set "
                    "ML_TRAINER_TPU_VOCAB_DIR to make this explicit",
                    stacklevel=2,
                )
        if tok is not None:
            if tok.vocab_size <= vocab_size:
                return encode_batch(tok, texts, max_len)
            # The caller's model embeds only ``vocab_size`` rows;
            # emitting larger ids would gather garbage SILENTLY (XLA
            # clamps out-of-range indices).  Skip the in-tree tokenizer
            # rather than poison training, and say why.
            import warnings

            warnings.warn(
                f"tokenizer in {resolved!r} has vocab_size "
                f"{tok.vocab_size} > the declared embedding size "
                f"{vocab_size}; falling back to the hash tokenizer. "
                f"Build the model with vocab_size={tok.vocab_size} to "
                "use it.",
                stacklevel=2,
            )
        if tok is None and tokenizer_name is not None:
            try:
                from transformers import AutoTokenizer

                hf = AutoTokenizer.from_pretrained(
                    tokenizer_name, local_files_only=True
                )
                enc = hf(
                    list(texts), max_length=max_len, padding="max_length",
                    truncation=True, return_tensors="np",
                )
                return (
                    enc["input_ids"].astype(np.int32),
                    enc["attention_mask"].astype(np.int32),
                )
            except Exception:
                pass  # fall through to the hash stand-in
        if tok is None:
            import warnings

            warnings.warn(
                "no tokenizer vocab found anywhere (vocab_dir, "
                "$ML_TRAINER_TPU_VOCAB_DIR, data/tokenizer/, repo "
                "fixtures); using the hash stand-in tokenizer — pass "
                "tokenizer='hash' to opt in explicitly and silence "
                "this warning",
                stacklevel=2,
            )
    ids = np.zeros((len(texts), max_len), np.int32)
    mask = np.zeros((len(texts), max_len), np.int32)
    for i, text in enumerate(texts):
        toks = [1] + _hash_tokenize(text, vocab_size)[: max_len - 2] + [2]
        ids[i, : len(toks)] = toks
        mask[i, : len(toks)] = 1
    return ids, mask


class TokenizedDataset(ArrayDataset):
    """[N, S] token ids with integer labels (sequence classification) —
    feeds BERT fine-tuning through the ordinary Loader."""

    def __init__(self, input_ids: np.ndarray, labels: np.ndarray,
                 attention_mask: Optional[np.ndarray] = None):
        super().__init__(np.asarray(input_ids, np.int32),
                         np.asarray(labels, np.int32))
        self.attention_mask = (
            None if attention_mask is None
            else np.asarray(attention_mask, np.int32)
        )

    @classmethod
    def from_texts(cls, texts: Sequence[str], labels: Sequence[int],
                   max_len: int = 128, tokenizer_name: Optional[str] = None,
                   vocab_size: int = 30522,
                   vocab_dir: Optional[str] = None,
                   tokenizer: str = "auto"):
        """``vocab_size`` bounds the offline tokenizer's ids — it MUST match
        the model's embedding table (out-of-range ids gather garbage).
        By default the in-tree tokenizers encode (fixture vocabs as last
        resort); ``tokenizer='hash'`` opts into the hash stand-in."""
        ids, mask = tokenize_texts(
            texts, max_len, tokenizer_name, vocab_size, vocab_dir,
            tokenizer=tokenizer,
        )
        return cls(ids, np.asarray(labels), mask)


def load_sst2_tsv(path: str, max_len: int = 128,
                  tokenizer_name: Optional[str] = None,
                  vocab_size: int = 30522,
                  vocab_dir: Optional[str] = None,
                  tokenizer: str = "auto") -> TokenizedDataset:
    """GLUE SST-2 ``train.tsv``/``dev.tsv`` (header, sentence\\tlabel)."""
    texts, labels = [], []
    with open(path) as fp:
        header = fp.readline()
        for line in fp:
            sentence, _, label = line.rstrip("\n").rpartition("\t")
            if sentence:
                texts.append(sentence)
                labels.append(int(label))
    return TokenizedDataset.from_texts(
        texts, labels, max_len, tokenizer_name, vocab_size, vocab_dir,
        tokenizer=tokenizer,
    )


def pack_texts(
    texts: Sequence[str],
    seq_len: int = 1024,
    vocab_dir: Optional[str] = None,
    eos_id: Optional[int] = None,
) -> "PackedLMDataset":
    """Tokenize ``texts`` with the in-tree tokenizer found in
    ``vocab_dir`` (see ``tokenize_texts`` discovery) and concatenate into
    a :class:`PackedLMDataset` — the GPT-2 pretraining data path with
    real tokenization.  ``eos_id`` (if given) separates documents in the
    stream, the byte-level-BPE convention."""
    from ml_trainer_tpu.data.tokenizers import (
        load_tokenizer,
        resolve_vocab_dir,
    )

    vocab_dir = resolve_vocab_dir(vocab_dir)
    tok = load_tokenizer(vocab_dir)
    if tok is None:
        raise FileNotFoundError(
            f"no tokenizer files (vocab.json+merges.txt or vocab.txt) "
            f"in {vocab_dir!r}"
        )
    stream: List[int] = []
    for text in texts:
        stream.extend(tok.encode(text))
        if eos_id is not None:
            stream.append(eos_id)
    return PackedLMDataset(np.asarray(stream, np.int32), seq_len)


class PackedLMDataset(ArrayDataset):
    """Concatenated token stream chunked into [N, seq_len] blocks with
    next-token targets — the GPT-2 pretraining layout."""

    def __init__(self, token_stream: np.ndarray, seq_len: int = 1024):
        stream = np.asarray(token_stream, np.int32).ravel()
        n = (len(stream) - 1) // seq_len
        if n < 1:
            raise ValueError(
                f"token stream of {len(stream)} tokens too short for "
                f"seq_len={seq_len}"
            )
        data = stream[: n * seq_len].reshape(n, seq_len)
        targets = stream[1 : n * seq_len + 1].reshape(n, seq_len)
        super().__init__(data, targets)
