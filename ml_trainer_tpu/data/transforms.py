"""Vectorized host-side batch transforms (NHWC numpy).

The reference applies torchvision transforms per sample inside DataLoader
workers (ref: src/utils/functions.py:5-12).  Per-sample Python transforms are
a throughput hazard for a TPU input pipeline, so each transform here operates
on a whole batch ``[B, H, W, C]`` with vectorized numpy and an explicit
``np.random.Generator`` — deterministic given the seed, matching the
reference's seeded-run spirit (ref: src/trainer.py:47) without torch's
worker nondeterminism.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np


class Transform:
    def __call__(self, batch: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        raise NotImplementedError


class Compose(Transform):
    """Sequential composition (torchvision.transforms.Compose analog)."""

    def __init__(self, transforms: Iterable[Transform]):
        self.transforms = list(transforms)

    def __call__(self, batch, rng):
        for t in self.transforms:
            batch = t(batch, rng)
        return batch

    def __repr__(self):
        return f"Compose({self.transforms})"


class RandomCrop(Transform):
    """Random crop with reflection-free zero padding, one offset per sample
    (torchvision RandomCrop(size, padding) semantics, ref:
    src/utils/functions.py:7)."""

    def __init__(self, size: int, padding: int = 0):
        self.size = size
        self.padding = padding

    def __call__(self, batch, rng):
        b, h, w, c = batch.shape
        p, s = self.padding, self.size
        if p:
            batch = np.pad(
                batch, ((0, 0), (p, p), (p, p), (0, 0)), mode="constant"
            )
        max_off = batch.shape[1] - s, batch.shape[2] - s
        oy = rng.integers(0, max_off[0] + 1, size=b)
        ox = rng.integers(0, max_off[1] + 1, size=b)
        # (B, offy, offx, C, s, s) view; one gather per batch, no Python loop.
        windows = np.lib.stride_tricks.sliding_window_view(batch, (s, s), axis=(1, 2))
        out = windows[np.arange(b), oy, ox]  # (B, C, s, s)
        return np.ascontiguousarray(out.transpose(0, 2, 3, 1))


class RandomHorizontalFlip(Transform):
    """Flip each sample left-right with probability ``p`` (ref:
    src/utils/functions.py:8)."""

    def __init__(self, p: float = 0.5):
        self.p = p

    def __call__(self, batch, rng):
        mask = rng.random(batch.shape[0]) < self.p
        out = batch.copy()
        out[mask] = out[mask, :, ::-1]
        return out


class ToFloat(Transform):
    """uint8 [0, 255] -> float32 [0, 1]; NHWC is kept (torchvision ToTensor
    additionally transposes to CHW — channels-last is the TPU-native layout,
    documented divergence)."""

    def __call__(self, batch, rng):
        if batch.dtype == np.uint8:
            return batch.astype(np.float32) / 255.0
        return batch.astype(np.float32)


class Normalize(Transform):
    """Per-channel (x - mean) / std (ref: src/utils/functions.py:10)."""

    def __init__(self, mean: Sequence[float], std: Sequence[float]):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)

    def __call__(self, batch, rng):
        return (batch - self.mean) / self.std


class ForeignTransform(Transform):
    """Adapter for per-sample transforms with a foreign signature — e.g. a
    torchvision ``Compose`` carried by a reference-style dataset
    (ref: main.py:14-18).  Applies the callable sample-by-sample, converts
    torch CHW tensors back to NHWC numpy, and restacks the batch.  Slower
    than the vectorized transforms above, but keeps the reference notebook
    flow working unmodified."""

    def __init__(self, fn):
        self.fn = fn

    @staticmethod
    def _to_pil(sample):
        try:
            from PIL import Image

            return Image.fromarray(sample)
        except ImportError:
            return sample

    def __call__(self, batch, rng):
        out = []
        for sample in batch:
            if sample.dtype == np.uint8 and sample.ndim == 3:
                sample = self._to_pil(sample)  # torchvision ops expect PIL
            x = self.fn(sample)
            if hasattr(x, "numpy"):  # torch tensor, CHW float
                x = x.numpy()
                if x.ndim == 3 and x.shape[0] in (1, 3):
                    x = x.transpose(1, 2, 0)
            out.append(np.asarray(x))
        return np.stack(out)
