"""Loader — batched, shuffled, sampler-aware iteration plus device prefetch.

Plays the role of the reference's ``Loader(DataLoader)`` extension point
(ref: src/dataloader.py:5-10) and its construction sites
(ref: src/trainer.py:77-79).  Differences by design:

* batches are assembled by vectorized numpy gathers over an epoch-level
  index permutation — no worker processes, no per-sample Python;
* batched transforms run on the assembled batch (see data/transforms.py);
* ``prefetch_to_device`` double-buffers ``jax.device_put`` (optionally with
  a ``NamedSharding`` that splits the global batch over the mesh's data
  axis) so host→HBM copies overlap compute — the TPU equivalent of pinned
  memory + workers in torch's DataLoader.
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
from typing import Iterator, Optional, Tuple

import jax
import numpy as np

from ml_trainer_tpu.data.datasets import Dataset, as_dataset
from ml_trainer_tpu.data.sampler import ShardedSampler


class _TrivialSampler:
    """Full-dataset sampler used when no distributed sampler is given —
    exists so ``len(loader.sampler)`` works for the reference's
    data-coverage logs (ref: src/trainer.py:80-93)."""

    def __init__(self, n: int):
        self.n = n

    def __len__(self):
        return self.n


class Loader:
    def __init__(
        self,
        dataset,
        batch_size: int = 1,
        shuffle: bool = False,
        sampler: Optional[ShardedSampler] = None,
        drop_last: bool = False,
        seed: int = 0,
    ):
        self.dataset: Dataset = as_dataset(dataset)
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self._sampler = sampler
        self.drop_last = drop_last
        self.seed = seed
        self._epoch = 0

    @property
    def sampler(self):
        return self._sampler if self._sampler is not None else _TrivialSampler(
            len(self.dataset)
        )

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch
        if self._sampler is not None:
            self._sampler.set_epoch(epoch)

    def _indices(self) -> np.ndarray:
        if self._sampler is not None:
            return np.asarray(self._sampler.indices())
        if self.shuffle:
            rng = np.random.default_rng((self.seed, self._epoch))
            return rng.permutation(len(self.dataset))
        return np.arange(len(self.dataset))

    def __len__(self) -> int:
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return -(-n // self.batch_size)

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        idx = self._indices()
        n_batches = len(self)
        transform = getattr(self.dataset, "transform", None)
        rng = np.random.default_rng((self.seed, 1 + self._epoch))
        # Vectorized-gather path: ArrayDataset and the memory-mapped
        # ShardedImageDataset both expose batch(indices).  callable():
        # a user dataset with an unrelated ``batch`` ATTRIBUTE (say an
        # int batch size) must keep the per-item path.
        fast = callable(getattr(self.dataset, "batch", None))
        for b in range(n_batches):
            sel = idx[b * self.batch_size : (b + 1) * self.batch_size]
            if fast:
                x, y = self.dataset.batch(sel)
            else:
                xs, ys = zip(*[self.dataset[int(i)] for i in sel])
                x, y = np.stack(xs), np.asarray(ys)
            if transform is not None:
                x = transform(x, rng)
            yield x, y


# Data-loader lag accounting: host seconds spent blocked in the underlying
# iterator (the ``data_load`` span), per batch.  The per-host heartbeat
# (telemetry/cluster.py) reads this — a pod host whose loader-wait climbs
# while its step time holds is input-bound, not compute-straggling.
_wait_lock = threading.Lock()
_wait_secs = 0.0
_wait_batches = 0


def loader_wait_snapshot() -> Tuple[float, int]:
    """Cumulative (seconds-blocked, batches-loaded) of every
    ``prefetch_to_device`` iterator in this process."""
    with _wait_lock:
        return _wait_secs, _wait_batches


def _record_wait(secs: float) -> None:
    global _wait_secs, _wait_batches
    with _wait_lock:
        _wait_secs += secs
        _wait_batches += 1
    # Goodput ledger (telemetry/goodput.py): host seconds blocked in the
    # input pipeline are non-compute wall-clock by definition.
    from ml_trainer_tpu.telemetry import goodput

    goodput.account("data_wait", secs)


def prefetch_to_device(
    iterator,
    size: int = 2,
    sharding=None,
):
    """Double-buffered host→device transfer.

    Keeps ``size`` batches in flight: while the TPU runs step N, the host is
    already copying batch N+1 into HBM.  ``sharding`` (a ``NamedSharding``
    over the mesh's data axis) makes the same call the global-batch splitter
    for the distributed path — the role DistributedSampler + DDP input
    scattering plays in the reference (ref: src/trainer.py:60-64).
    """
    queue = collections.deque()
    multi_host = jax.process_count() > 1

    def fit_rank(a, s):
        if s is None:
            return s
        from ml_trainer_tpu.parallel.sharding import fit_sharding_to_rank

        return fit_sharding_to_rank(s, np.ndim(a))

    def put(batch):
        if sharding is None:
            return jax.tree.map(jax.device_put, batch)
        if multi_host:
            # Each host contributes its sampler shard of the global batch —
            # the assembled jax.Array spans the whole mesh (the reference
            # reaches the same global batch via DistributedSampler + DDP,
            # ref: src/trainer.py:60-64).
            return jax.tree.map(
                lambda a: jax.make_array_from_process_local_data(
                    fit_rank(a, sharding), np.asarray(a)
                ),
                batch,
            )
        return jax.tree.map(
            lambda a: jax.device_put(a, fit_rank(a, sharding)), batch
        )

    # Host spans (telemetry/spans.py): data_load is the host assembling
    # the next batch, h2d its device placement — on the Perfetto
    # timeline these show whether the input pipeline hides behind the
    # step or the step waits on it.
    from ml_trainer_tpu.telemetry.spans import span

    it = iter(iterator)

    def load_next():
        t0 = time.perf_counter()
        with span("data_load"):
            batch = next(it, None)
        _record_wait(time.perf_counter() - t0)
        return batch

    from ml_trainer_tpu.telemetry import goodput

    def put_spanned(batch):
        with span("h2d"), goodput.timed("h2d"):
            return put(batch)

    for batch in itertools.islice(it, size):
        queue.append(put_spanned(batch))
    while queue:
        yield queue.popleft()
        batch = load_next()
        if batch is not None:
            queue.append(put_spanned(batch))
