"""ctypes bindings for the native batch worker (csrc/batch_worker.cpp).

``NativeLoader`` is a drop-in alternative to the Python ``Loader`` for
uint8-image array datasets: batch assembly (gather + crop + flip +
normalize) runs in C++ threads that stay ``queue_cap`` batches ahead of the
training loop — the torch DataLoader worker-pool role (SURVEY.md §2B)
without worker processes or pickling.  The shared library is built with g++
on first use if missing.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

from ml_trainer_tpu.data.datasets import ArrayDataset
from ml_trainer_tpu.data.sampler import ShardedSampler

_CSRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc")
_LIB_PATH = os.path.abspath(os.path.join(_CSRC, "libbatch_worker.so"))
_lib = None
_lib_lock = threading.Lock()


_SOURCES = ("batch_worker.cpp", "jpeg_decoder.cpp")


def _build_library() -> str:
    srcs = [os.path.join(_CSRC, s) for s in _SOURCES]
    # Compile to a private temp path, then atomically publish: concurrent
    # processes (parallel pytest, multi-process workers) may rebuild at
    # the same time, and one must never dlopen a half-written .so.
    tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
    subprocess.run(
        ["g++", "-O3", "-std=c++17", "-fPIC", "-pthread", "-Wall", "-shared",
         "-o", tmp, *srcs],
        check=True,
        capture_output=True,
    )
    os.replace(tmp, _LIB_PATH)
    return _LIB_PATH


def load_library() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        srcs = [os.path.join(_CSRC, s) for s in _SOURCES]
        if not os.path.exists(_LIB_PATH) or any(
            os.path.exists(s)
            and os.path.getmtime(s) > os.path.getmtime(_LIB_PATH)
            for s in srcs
        ):
            _build_library()
        lib = ctypes.CDLL(_LIB_PATH)
        lib.batch_worker_create_sharded.restype = ctypes.c_void_p
        lib.batch_worker_create_sharded.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
        ]
        lib.batch_worker_start_epoch.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_uint64,
        ]
        lib.batch_worker_next.restype = ctypes.c_int64
        lib.batch_worker_next.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.batch_worker_destroy.argtypes = [ctypes.c_void_p]
        lib.batch_worker_create_jpeg.restype = ctypes.c_void_p
        lib.batch_worker_create_jpeg.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
        ]
        lib.batch_worker_decode_errors.restype = ctypes.c_int64
        lib.batch_worker_decode_errors.argtypes = [ctypes.c_void_p]
        lib.jpeg_decode_expect.restype = ctypes.c_int
        lib.jpeg_decode_expect.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int, ctypes.c_int,
        ]
        _lib = lib
        return lib


def native_available() -> bool:
    try:
        load_library()
        return True
    except Exception:
        return False


def jpeg_decode_np(data, shape) -> Optional[np.ndarray]:
    """Decode one baseline-JPEG byte buffer to uint8 [H, W, 3] through
    the NATIVE decoder — the same code path the C++ worker threads run,
    so Python-side decodes are bit-equal to worker batches.  Returns
    None when the native library is unavailable (callers fall back to
    PIL) and raises on a corrupt stream."""
    try:
        lib = load_library()
    except Exception:
        return None
    data = np.ascontiguousarray(np.frombuffer(bytes(data), np.uint8))
    out = np.empty(shape, np.uint8)
    rc = lib.jpeg_decode_expect(
        data.ctypes.data_as(ctypes.c_void_p), len(data),
        out.ctypes.data_as(ctypes.c_void_p), out.size,
        int(shape[1]), int(shape[0]),
    )
    if rc != 0:
        raise ValueError(f"jpeg_decode failed (rc={rc})")
    return out


def native_plan(dataset) -> Optional[dict]:
    """NativeLoader kwargs if this dataset can run through the fused C++
    pipeline with IDENTICAL semantics to the Python Loader + its transform:
    uint8 NHWC array data whose transform is the reference augmentation
    (RandomCrop(p=4)? + RandomHorizontalFlip? + ToFloat + Normalize,
    ref: src/utils/functions.py:5-12).  Returns None when the Python path
    must be used (foreign/no transform, float data, non-default flip p,
    crop size != image size)."""
    from ml_trainer_tpu.data.transforms import (
        Compose,
        Normalize,
        RandomCrop,
        RandomHorizontalFlip,
        ToFloat,
    )

    from ml_trainer_tpu.data.sharded import (
        ShardedImageDataset,
        ShardedJpegDataset,
    )

    data = getattr(dataset, "data", None)
    if isinstance(dataset, (ShardedImageDataset, ShardedJpegDataset)):
        # Memory-mapped shards: the native worker gathers from the mapped
        # segments directly (the beyond-RAM path); jpeg shards decode on
        # the worker threads first.
        if len(dataset.shape) != 3:
            return None
        h, w = dataset.shape[0], dataset.shape[1]
    elif (
        isinstance(data, np.ndarray)
        and data.dtype == np.uint8
        and data.ndim == 4
    ):
        h, w = data.shape[1], data.shape[2]
    else:
        return None
    t = getattr(dataset, "transform", None)
    if t is None:
        return None
    ts = list(t.transforms) if isinstance(t, Compose) else [t]
    i, pad, flip = 0, 0, False
    if i < len(ts) and isinstance(ts[i], RandomCrop):
        if ts[i].size != h or h != w:
            return None
        pad, i = ts[i].padding, i + 1
    if i < len(ts) and isinstance(ts[i], RandomHorizontalFlip):
        if ts[i].p != 0.5:
            return None
        flip, i = True, i + 1
    if not (i < len(ts) and isinstance(ts[i], ToFloat)):
        return None
    i += 1
    if not (i < len(ts) and isinstance(ts[i], Normalize)):
        return None
    normalize = (tuple(ts[i].mean.tolist()), tuple(ts[i].std.tolist()))
    i += 1
    if i != len(ts):
        return None
    return dict(pad=pad, flip=flip, normalize=normalize)


class NativeLoader:
    """C++-threaded Loader for uint8 NHWC image datasets.

    Mirrors the Python ``Loader`` iteration contract (len, set_epoch,
    yields (images, labels) numpy batches) with the reference's CIFAR-10
    augmentation fused into the native pass (crop pad 4 / flip / normalize,
    ref: src/utils/functions.py:5-12).
    """

    def __init__(
        self,
        dataset: ArrayDataset,
        batch_size: int,
        shuffle: bool = True,
        sampler: Optional[ShardedSampler] = None,
        pad: int = 4,
        flip: bool = True,
        normalize: Optional[Tuple[Tuple[float, ...], Tuple[float, ...]]] = None,
        num_threads: int = 4,
        queue_cap: int = 8,
        seed: int = 0,
        drop_last: bool = True,
    ):
        from ml_trainer_tpu.data.sharded import (
            ShardedImageDataset,
            ShardedJpegDataset,
        )

        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self._sampler = sampler
        self.seed = seed
        self.drop_last = drop_last
        self._epoch = 0
        self._jpeg = isinstance(dataset, ShardedJpegDataset)
        # Decode-error accounting baseline: the C++ counter is CUMULATIVE
        # across epochs, so every check compares against this snapshot
        # (taken at each epoch start) rather than the raw value —
        # otherwise an early ``break`` defers one epoch's corrupt samples
        # into a later epoch's raise.
        self._err_base = 0
        if self._jpeg:
            # Compressed path: segments are the mapped JPEG byte blobs;
            # per-segment offset tables locate each sample's stream.
            # Worker threads decode (csrc/jpeg_decoder.cpp) before the
            # fused augmentation — pixels exist only for in-flight
            # batches.
            if len(dataset.shape) != 3 or dataset.shape[2] != 3:
                raise ValueError("jpeg NativeLoader requires HWC RGB")
            self._segments = list(dataset.byte_maps)
            self._offsets = [
                np.ascontiguousarray(o, np.int64)
                for o in dataset.offset_tables
            ]
            h, w, c = dataset.shape
            seg_starts = dataset.shard_starts[:-1]
        elif isinstance(dataset, ShardedImageDataset):
            # Beyond-RAM path: the worker gathers straight from the
            # memory-mapped shard segments — the dataset is never copied
            # into process RAM.  (np.ascontiguousarray on a C-contiguous
            # memmap is a no-copy passthrough; keep references so the
            # mappings outlive the C++ worker.)
            if len(dataset.shape) != 3:
                raise ValueError("NativeLoader requires uint8 NHWC images")
            self._segments = [
                np.ascontiguousarray(m) for m in dataset.shard_maps
            ]
            h, w, c = dataset.shape
            seg_starts = dataset.shard_starts[:-1]
        else:
            if dataset.data.dtype != np.uint8 or dataset.data.ndim != 4:
                raise ValueError("NativeLoader requires uint8 NHWC image data")
            self._segments = [np.ascontiguousarray(dataset.data)]
            _, h, w, c = self._segments[0].shape
            seg_starts = [0]
        self._labels = np.ascontiguousarray(dataset.targets.astype(np.int32))
        self._shape = (h, w, c)
        if normalize is None:
            from ml_trainer_tpu.utils.functions import CIFAR10_MEAN, CIFAR10_STD

            normalize = (CIFAR10_MEAN, CIFAR10_STD)
        mean = (ctypes.c_float * c)(*normalize[0][:c])
        std = (ctypes.c_float * c)(*normalize[1][:c])
        lib = load_library()
        self._lib = lib
        n_segs = len(self._segments)
        seg_ptrs = (ctypes.c_void_p * n_segs)(
            *[s.ctypes.data for s in self._segments]
        )
        starts = (ctypes.c_int64 * n_segs)(*[int(s) for s in seg_starts])
        if self._jpeg:
            off_ptrs = (ctypes.c_void_p * n_segs)(
                *[o.ctypes.data for o in self._offsets]
            )
            self._handle = lib.batch_worker_create_jpeg(
                ctypes.cast(seg_ptrs, ctypes.POINTER(ctypes.c_void_p)),
                ctypes.cast(off_ptrs, ctypes.POINTER(ctypes.c_void_p)),
                ctypes.cast(starts, ctypes.POINTER(ctypes.c_int64)),
                n_segs,
                self._labels.ctypes.data_as(ctypes.c_void_p),
                len(dataset), h, w, c, pad, int(flip), 1, mean, std,
                self.batch_size, num_threads, queue_cap, seed + 1,
            )
        else:
            self._handle = lib.batch_worker_create_sharded(
                ctypes.cast(seg_ptrs, ctypes.POINTER(ctypes.c_void_p)),
                ctypes.cast(starts, ctypes.POINTER(ctypes.c_int64)),
                n_segs,
                self._labels.ctypes.data_as(ctypes.c_void_p),
                len(dataset), h, w, c, pad, int(flip), 1, mean, std,
                self.batch_size, num_threads, queue_cap, seed + 1,
            )
        if not self._handle:
            raise RuntimeError("native batch worker creation failed")

    @property
    def sampler(self):
        from ml_trainer_tpu.data.loader import _TrivialSampler

        return self._sampler if self._sampler is not None else _TrivialSampler(
            len(self.dataset)
        )

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch
        if self._sampler is not None:
            self._sampler.set_epoch(epoch)

    def __len__(self) -> int:
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def _indices(self) -> np.ndarray:
        if self._sampler is not None:
            return np.asarray(self._sampler.indices(), np.int64)
        if self.shuffle:
            rng = np.random.default_rng((self.seed, self._epoch))
            return rng.permutation(len(self.dataset)).astype(np.int64)
        return np.arange(len(self.dataset), dtype=np.int64)

    def __iter__(self):
        n_batches = len(self)
        need = n_batches * self.batch_size
        idx = self._indices().astype(np.int64, copy=False)
        if idx.size < need:
            # drop_last=False with a ragged tail: the C++ side
            # unconditionally copies n_batches*batch_size indices
            # (csrc/batch_worker.cpp start_epoch), so pad by wrapping —
            # same convention as ShardedSampler — rather than hand it a
            # short buffer (out-of-bounds read).  The final batch then
            # repeats leading samples instead of being short.
            idx = np.resize(idx, need)
        idx = np.ascontiguousarray(idx[:need], np.int64)
        if self._jpeg:
            # Re-baseline BEFORE the epoch runs: errors left unobserved by
            # a prior epoch's early break belong to that epoch, not this
            # one (stop()/__del__ surface them instead).
            self._err_base = self._lib.batch_worker_decode_errors(
                self._handle
            )
        self._lib.batch_worker_start_epoch(
            self._handle,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n_batches,
            self._epoch,
        )
        h, w, c = self._shape
        for _ in range(n_batches):
            images = np.empty((self.batch_size, h, w, c), np.float32)
            labels = np.empty((self.batch_size,), np.int32)
            got = self._lib.batch_worker_next(
                self._handle,
                images.ctypes.data_as(ctypes.c_void_p),
                labels.ctypes.data_as(ctypes.c_void_p),
            )
            if got < 0:
                return
            yield images, labels
        errs = self._decode_error_delta()
        # decode_error injection hook (resilience/faults.py): exercise the
        # corrupt-sample accounting path deterministically in tests —
        # identical semantics to real C++-counted decode failures.
        from ml_trainer_tpu.resilience.faults import active_plan

        plan = active_plan()
        if plan is not None and plan.fire(
            "decode_error", epoch=self._epoch
        ) is not None:
            errs += 1
        if errs:
            # Corrupt streams were zero-filled to keep shapes; fail
            # the epoch loudly rather than train on silent zeros.
            raise RuntimeError(
                f"{errs} sample(s) failed JPEG decode this epoch"
            )

    def _decode_error_delta(self) -> int:
        """New decode errors since the last check (delta against the
        cumulative C++ counter; consumes what it reports)."""
        if not self._jpeg or not getattr(self, "_handle", None):
            return 0
        errs = int(self._lib.batch_worker_decode_errors(self._handle))
        delta = errs - self._err_base
        self._err_base = errs
        return delta

    def stop(self) -> None:
        """Tear down the C++ worker now (idempotent).  Raises if decode
        errors accumulated since the last check — a consumer that broke
        out of an epoch early still hears about its corrupt samples."""
        handle = getattr(self, "_handle", None)
        if not handle:
            return
        errs = self._decode_error_delta()
        self._lib.batch_worker_destroy(handle)
        self._handle = None
        if errs:
            raise RuntimeError(
                f"{errs} sample(s) failed JPEG decode since the last check"
            )

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            errs = self._decode_error_delta()
            self._lib.batch_worker_destroy(handle)
            self._handle = None
            if errs:
                # Raising in __del__ is unraisable noise; warn instead so
                # the corruption is at least visible.
                import warnings

                warnings.warn(
                    f"NativeLoader destroyed with {errs} unreported JPEG "
                    "decode error(s)"
                )
