"""ShardedSampler — the DistributedSampler analog.

The reference shards the training set with
``DistributedSampler(num_replicas=world_size, rank=rank)`` and divides the
global batch by the world size (ref: src/trainer.py:60-64).  On TPU the
replica boundary that matters for the *host-side* pipeline is the process
(host): each host materializes its shard of the global batch and the mesh
sharding of ``device_put`` splits it further across local chips.  This
sampler reproduces torch's semantics: epoch-seeded shuffle, padding so every
replica sees the same number of samples, ``set_epoch`` for reshuffling.
"""

from __future__ import annotations

import numpy as np


class ShardedSampler:
    def __init__(
        self,
        dataset_len: int,
        num_replicas: int = 1,
        rank: int = 0,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if not 0 <= rank < num_replicas:
            raise ValueError(f"rank {rank} out of range for {num_replicas} replicas")
        self.dataset_len = dataset_len
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last:
            self.num_samples = dataset_len // num_replicas
        else:
            self.num_samples = -(-dataset_len // num_replicas)  # ceil
        self.total_size = self.num_samples * num_replicas

    def set_epoch(self, epoch: int) -> None:
        """Reshuffle deterministically per epoch (torch DistributedSampler
        contract)."""
        self.epoch = epoch

    def indices(self) -> np.ndarray:
        if self.shuffle:
            rng = np.random.default_rng((self.seed, self.epoch))
            order = rng.permutation(self.dataset_len)
        else:
            order = np.arange(self.dataset_len)
        if self.drop_last:
            order = order[: self.total_size]
        elif len(order) < self.total_size:
            # Pad by wrapping (torch pads with the head of the permutation).
            order = np.concatenate([order, order[: self.total_size - len(order)]])
        return order[self.rank : self.total_size : self.num_replicas]

    def __iter__(self):
        return iter(self.indices())

    def __len__(self):
        return self.num_samples
