"""In-tree tokenizers: byte-level BPE (GPT-2 format) and WordPiece (BERT
format) — pure Python + numpy, loading LOCAL vocab files.

The reference has no text pipeline; this framework's BERT/GPT-2
north-star paths need real tokenization, and this zero-egress
environment cannot download pretrained tokenizers (VERDICT r4 weak #4:
the hash stand-in in ``text.py`` was the only offline option).  Both
implementations read the exact public file formats —
``vocab.json``/``merges.txt`` for byte-level BPE, ``vocab.txt`` for
WordPiece — so dropping in the real GPT-2/BERT files upgrades the data
path without a code change, and ``transformers``' slow tokenizers
loading the SAME files are the parity oracle in
tests/test_tokenizers.py.

Design notes (algorithms are public; implementations are fresh):

* Byte-level BPE: text is pre-tokenized GPT-2-style (contractions,
  optional-space letter/number/symbol runs, whitespace splitting), each
  pre-token's UTF-8 bytes are mapped through the printable-byte
  remapping, then merged lowest-rank-first per ``merges.txt``.  Decoding
  inverts exactly — byte-level coverage means round-trip is lossless for
  ANY input text.
* WordPiece: BERT basic tokenization (lowercase + accent-strip when
  ``do_lower_case``, punctuation split, CJK isolation), then greedy
  longest-match-first with ``##`` continuations; words that cannot be
  pieced become ``[UNK]``.
"""

from __future__ import annotations

import json
import os
import unicodedata
from functools import lru_cache
from typing import Dict, List, Optional, Sequence, Tuple


# --------------------------------------------------------------- byte level
@lru_cache(maxsize=1)
def _byte_encoder() -> Dict[int, str]:
    """Reversible byte -> printable-unicode-char map (the GPT-2 trick:
    BPE vocab files store tokens as text, so raw bytes that are
    whitespace/control chars are shifted to printable codepoints)."""
    keep = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("¡"), ord("¬") + 1))
        + list(range(ord("®"), ord("ÿ") + 1))
    )
    mapping = {b: chr(b) for b in keep}
    shift = 0
    for b in range(256):
        if b not in mapping:
            mapping[b] = chr(256 + shift)
            shift += 1
    return mapping


@lru_cache(maxsize=1)
def _byte_decoder() -> Dict[str, int]:
    return {c: b for b, c in _byte_encoder().items()}


_CONTRACTIONS = ("'s", "'t", "'re", "'ve", "'m", "'ll", "'d")


def _char_class(ch: str) -> str:
    """'L' (letter), 'N' (number), or 'O' (symbol) — the three run
    classes of the GPT-2 pre-tokenizer (\\p{L} / \\p{N} / neither)."""
    cat = unicodedata.category(ch)
    if cat.startswith("L"):
        return "L"
    if cat.startswith("N"):
        return "N"
    return "O"


def pretokenize(text: str) -> List[str]:
    """GPT-2's regex pre-tokenizer as an explicit scanner.

    Faithful to the published pattern (contractions first; ``' ?'`` +
    maximal same-class run; a whitespace run keeps its LAST char out
    when a token follows — that trailing space prefixes the next run —
    and only a literal space can prefix a run).  Parity with
    ``transformers.GPT2Tokenizer`` over the fixture vocab is pinned in
    tests."""
    toks: List[str] = []
    i, n = 0, len(text)
    while i < n:
        for c in _CONTRACTIONS:
            if text.startswith(c, i):
                toks.append(c)
                i += len(c)
                break
        else:
            ch = text[i]
            start = i
            if ch == " " and i + 1 < n and not text[i + 1].isspace():
                i += 1  # ' ?' — a single literal space joins the run
                ch = text[i]
            if ch.isspace():
                j = i
                while j < n and text[j].isspace():
                    j += 1
                if j < n and j - i > 1:
                    j -= 1  # \s+(?!\S): leave one char for the next run
                toks.append(text[start:j])
                i = j
                continue
            cls = _char_class(ch)
            j = i
            while j < n and not text[j].isspace():
                if _char_class(text[j]) != cls:
                    break
                j += 1
            # NOTE: contractions only win when the scan is AT the
            # apostrophe (top of loop) — inside a symbol run the regex
            # consumes the apostrophe into the run ("..'s" tokenizes as
            # "..'", "s", not "..", "'s"), so no mid-run break here.
            toks.append(text[start:j])
            i = j
    return toks


class ByteLevelBPETokenizer:
    """GPT-2-format byte-level BPE: ``vocab.json`` (token -> id) +
    ``merges.txt`` (one ranked merge pair per line)."""

    def __init__(self, vocab: Dict[str, int],
                 merges: Sequence[Tuple[str, str]]):
        self.vocab = dict(vocab)
        self.inv_vocab = {v: k for k, v in self.vocab.items()}
        self.ranks = {tuple(pair): r for r, pair in enumerate(merges)}
        self._cache: Dict[str, List[str]] = {}

    @classmethod
    def from_files(cls, vocab_file: str, merges_file: str):
        with open(vocab_file, encoding="utf-8") as fp:
            vocab = json.load(fp)
        # Byte-level coverage is the design invariant (any input byte
        # maps to SOME vocab entry, so encode cannot hit an unknown).
        # A truncated/non-byte-level vocab.json would otherwise fail
        # with a KeyError mid-corpus — fail at load instead.
        missing = [
            c for c in _byte_encoder().values() if c not in vocab
        ]
        if missing:
            raise ValueError(
                f"{vocab_file} is not a byte-level BPE vocab: "
                f"{len(missing)} of the 256 byte-alphabet symbols are "
                f"missing (first: {missing[0]!r})"
            )
        merges: List[Tuple[str, str]] = []
        with open(merges_file, encoding="utf-8") as fp:
            for line in fp:
                line = line.rstrip("\n")
                if not line or line.startswith("#version"):
                    continue
                a, _, b = line.partition(" ")
                merges.append((a, b))
        return cls(vocab, merges)

    @property
    def vocab_size(self) -> int:
        # max id + 1 (same bound the WordPiece property documents): the
        # embedding-size guard needs the largest emittable id.
        return max(self.vocab.values(), default=-1) + 1

    def _bpe(self, token: str) -> List[str]:
        """Merge the mapped-byte sequence of one pre-token, lowest
        merge-rank first, until no ranked pair remains."""
        cached = self._cache.get(token)
        if cached is not None:
            return cached
        parts = list(token)
        while len(parts) > 1:
            pairs = {(parts[k], parts[k + 1]) for k in range(len(parts) - 1)}
            best = min(
                pairs, key=lambda p: self.ranks.get(p, float("inf"))
            )
            if best not in self.ranks:
                break
            merged: List[str] = []
            k = 0
            while k < len(parts):
                if (
                    k + 1 < len(parts)
                    and (parts[k], parts[k + 1]) == best
                ):
                    merged.append(parts[k] + parts[k + 1])
                    k += 2
                else:
                    merged.append(parts[k])
                    k += 1
            parts = merged
        self._cache[token] = parts
        return parts

    def encode(self, text: str) -> List[int]:
        enc = _byte_encoder()
        ids: List[int] = []
        for pre in pretokenize(text):
            mapped = "".join(enc[b] for b in pre.encode("utf-8"))
            for piece in self._bpe(mapped):
                pid = self.vocab.get(piece)
                if pid is None:
                    # Only possible with a vocab/merges mismatch (a merge
                    # whose product is not in vocab.json) — name it.
                    raise ValueError(
                        f"merge product {piece!r} missing from vocab.json "
                        "— vocab/merges files are inconsistent"
                    )
                ids.append(pid)
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        dec = _byte_decoder()
        text = "".join(self.inv_vocab[int(i)] for i in ids)
        return bytes(dec[c] for c in text).decode("utf-8", errors="replace")


# --------------------------------------------------------------- wordpiece
def _strip_accents(text: str) -> str:
    return "".join(
        ch for ch in unicodedata.normalize("NFD", text)
        if unicodedata.category(ch) != "Mn"
    )


def _is_punctuation(ch: str) -> bool:
    cp = ord(ch)
    # ASCII symbol ranges count as punctuation for BERT even where
    # unicode disagrees (e.g. '$', '^', '`').
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp: int) -> bool:
    return (
        0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
        or 0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F
        or 0x2B740 <= cp <= 0x2B81F or 0x2B820 <= cp <= 0x2CEAF
        or 0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F
    )


class WordPieceTokenizer:
    """BERT-format WordPiece over a local ``vocab.txt`` (one token per
    line, line number = id)."""

    def __init__(self, vocab: Dict[str, int], do_lower_case: bool = True,
                 unk_token: str = "[UNK]", max_word_chars: int = 100):
        self.vocab = dict(vocab)
        self.inv_vocab = {v: k for k, v in self.vocab.items()}
        self.do_lower_case = do_lower_case
        self.unk_token = unk_token
        self.max_word_chars = max_word_chars
        self.cls_id = self.vocab.get("[CLS]")
        self.sep_id = self.vocab.get("[SEP]")
        self.pad_id = self.vocab.get("[PAD]", 0)

    @classmethod
    def from_files(cls, vocab_file: str, do_lower_case: bool = True):
        vocab: Dict[str, int] = {}
        with open(vocab_file, encoding="utf-8") as fp:
            for i, line in enumerate(fp):
                tok = line.rstrip("\n")
                if tok:
                    vocab[tok] = i
        return cls(vocab, do_lower_case)

    @property
    def vocab_size(self) -> int:
        # max id + 1, not len(): a vocab.txt with blank lines keeps
        # line-number ids, and the embedding-size guard in
        # tokenize_texts must bound the LARGEST id this tokenizer can
        # emit, not the entry count.
        return max(self.vocab.values(), default=-1) + 1

    def _basic_tokens(self, text: str) -> List[str]:
        # Control chars drop; CJK chars isolate; punctuation splits.
        cleaned: List[str] = []
        for ch in text:
            cp = ord(ch)
            # BERT's whitespace set is exactly " \t\n\r" + category Zs;
            # every OTHER category-C char (\x0b, \x0c, \x85, ...) is a
            # control char and DROPS — fusing its neighbors into one
            # word — even though Python's isspace() says otherwise.
            if ch in " \t\n\r" or unicodedata.category(ch) == "Zs":
                cleaned.append(" ")
            elif cp == 0 or cp == 0xFFFD or unicodedata.category(
                ch
            ).startswith("C"):
                continue
            elif _is_cjk(cp):
                cleaned.append(f" {ch} ")
            else:
                cleaned.append(ch)
        words: List[str] = []
        for word in "".join(cleaned).split():
            if self.do_lower_case:
                word = _strip_accents(word.lower())
            run = ""
            for ch in word:
                if _is_punctuation(ch):
                    if run:
                        words.append(run)
                        run = ""
                    words.append(ch)
                else:
                    run += ch
            if run:
                words.append(run)
        return words

    def _wordpiece(self, word: str) -> List[str]:
        if len(word) > self.max_word_chars:
            return [self.unk_token]
        pieces: List[str] = []
        start = 0
        while start < len(word):
            end = len(word)
            piece = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self.vocab:
                    piece = sub
                    break
                end -= 1
            if piece is None:
                return [self.unk_token]
            pieces.append(piece)
            start = end
        return pieces

    def tokenize(self, text: str) -> List[str]:
        return [
            p for w in self._basic_tokens(text) for p in self._wordpiece(w)
        ]

    def encode(self, text: str, add_special_tokens: bool = True
               ) -> List[int]:
        ids = []
        for t in self.tokenize(text):
            tid = self.vocab.get(t)
            if tid is None:
                # tokenize() only emits vocab entries or unk_token, so
                # this means the vocab lacks [UNK] itself — say so
                # instead of KeyError-ing mid-dataset-build.
                raise ValueError(
                    f"vocab.txt lacks the {t!r} token needed to encode "
                    "out-of-vocabulary words"
                )
            ids.append(tid)
        # Specials frame the sequence only when the vocab defines BOTH
        # (a custom vocab with [CLS] but no [SEP] must not emit None).
        if add_special_tokens and None not in (self.cls_id, self.sep_id):
            return [self.cls_id] + ids + [self.sep_id]
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        out: List[str] = []
        for i in ids:
            tok = self.inv_vocab.get(int(i), self.unk_token)
            if tok in ("[CLS]", "[SEP]", "[PAD]"):
                continue
            if tok.startswith("##"):
                out.append(tok[2:])
            else:
                if out:
                    out.append(" ")
                out.append(tok)
        return "".join(out)


# --------------------------------------------------------------- discovery
def fixture_vocab_dir() -> Optional[str]:
    """The repo's committed fixture vocabs (tests/fixtures/tokenizers:
    byte-BPE vocab.json+merges.txt AND WordPiece vocab.txt) — the
    zero-egress LAST-RESORT default, so the flagship text paths run real
    tokenization out of the box instead of the hash stand-in.  ``None``
    when the package is installed without the repo checkout."""
    root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    d = os.path.join(root, "tests", "fixtures", "tokenizers")
    return d if os.path.isdir(d) else None


def resolve_vocab_dir(vocab_dir: Optional[str] = None) -> str:
    """The single discovery policy: explicit argument, else
    ``$ML_TRAINER_TPU_VOCAB_DIR``, else ``data/tokenizer/`` relative to
    the working directory (the conventional drop-in spot for pretrained
    vocab files) when it exists, else the committed fixture vocabs
    (:func:`fixture_vocab_dir`)."""
    if vocab_dir:
        return vocab_dir
    env = os.environ.get("ML_TRAINER_TPU_VOCAB_DIR")
    if env:
        return env
    cwd_default = os.path.join("data", "tokenizer")
    if os.path.isdir(cwd_default):
        return cwd_default
    fix = fixture_vocab_dir()
    return fix if fix is not None else cwd_default


def load_tokenizer(vocab_dir: str, prefer: Optional[str] = None):
    """Build whichever tokenizer ``vocab_dir``'s files describe.

    ``vocab.json`` + ``merges.txt`` -> :class:`ByteLevelBPETokenizer`;
    ``vocab.txt`` -> :class:`WordPieceTokenizer`; neither -> ``None``.
    When BOTH file sets exist, BPE wins unless ``prefer='wordpiece'``
    (the BERT-shaped callers ask for WordPiece explicitly).  This is how
    ``tokenize_texts`` (data/text.py) discovers real tokenization."""
    if prefer not in (None, "bpe", "wordpiece"):
        raise ValueError(
            f"prefer must be None, 'bpe' or 'wordpiece', got {prefer!r}"
        )
    vj = os.path.join(vocab_dir, "vocab.json")
    mt = os.path.join(vocab_dir, "merges.txt")
    vt = os.path.join(vocab_dir, "vocab.txt")
    has_bpe = os.path.exists(vj) and os.path.exists(mt)
    has_wp = os.path.exists(vt)
    if has_wp and (prefer == "wordpiece" or not has_bpe):
        return WordPieceTokenizer.from_files(vt)
    if has_bpe:
        return ByteLevelBPETokenizer.from_files(vj, mt)
    return None


def encode_batch(
    tokenizer, texts: Sequence[str], max_len: int,
    pad_id: Optional[int] = None,
):
    """(input_ids [N, max_len], attention_mask [N, max_len]) int32 —
    truncate + right-pad, special-token framing where the tokenizer
    defines it (WordPiece [CLS]/[SEP]; BPE none, like GPT-2)."""
    import numpy as np

    if pad_id is None:
        pad_id = getattr(tokenizer, "pad_id", 0)
    ids = np.full((len(texts), max_len), pad_id, np.int32)
    mask = np.zeros((len(texts), max_len), np.int32)
    for i, text in enumerate(texts):
        row = tokenizer.encode(text)
        if isinstance(tokenizer, WordPieceTokenizer) and (
            len(row) > max_len
        ):
            # Keep the [SEP] terminator under truncation, like BERT.
            row = row[: max_len - 1] + [row[-1]]
        row = row[:max_len]
        ids[i, : len(row)] = row
        mask[i, : len(row)] = 1
    return ids, mask
