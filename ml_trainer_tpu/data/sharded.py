"""Beyond-RAM dataset: sharded on-disk ``.npy`` images, memory-mapped.

The reference's DataLoader role (ref: src/dataloader.py:5 — arbitrary
dataset objects through a worker pool) covers datasets that do not fit in
host RAM; the in-memory ``ArrayDataset`` does not.  This module adds the
ImageNet-class path (BASELINE.json configs[1]): images live in per-shard
``.npy`` files and are **memory-mapped**, so batch gathers fault in only
the pages they touch and the OS page cache — not the Python process —
decides residency.  Labels (4 bytes/sample) stay in RAM.

Layout of a dataset directory::

    index.json                {"shards": [{"x": ..., "y": ..., "n": ...}],
                               "shape": [H, W, C], "total": N}
    shard_00000_x.npy         [n, H, W, C] uint8 images
    shard_00000_y.npy         [n] int32 labels
    ...

Both loaders consume it: the Python ``Loader`` through ``batch()``
(per-shard fancy-indexing into the maps), and the C++ ``NativeLoader``
through a shard pointer table (csrc/batch_worker.cpp gathers straight
from the mapped pages on its worker threads — sustained prefetch with no
copy of the dataset into RAM).
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Optional, Tuple

import numpy as np

from ml_trainer_tpu.data.datasets import Dataset
from ml_trainer_tpu.data.transforms import Transform

INDEX_FILE = "index.json"


def write_sharded_dataset(
    root: str,
    batches: Iterable[Tuple[np.ndarray, np.ndarray]],
    samples_per_shard: int = 8192,
) -> str:
    """Write an iterable of (images [n,H,W,C] uint8, labels [n]) chunks as
    a sharded dataset under ``root``.  Chunks are re-chunked to exactly
    ``samples_per_shard`` per shard (last shard ragged), so the writer
    itself is streaming: peak RAM is one shard, regardless of dataset
    size."""
    os.makedirs(root, exist_ok=True)
    shards, shape = [], None
    buf_x: list = []
    buf_y: list = []
    buffered = 0

    def flush(n):
        nonlocal buffered
        cat_x, cat_y = np.concatenate(buf_x), np.concatenate(buf_y)
        x, rest_x = cat_x[:n], cat_x[n:]
        y, rest_y = cat_y[:n].astype(np.int32), cat_y[n:]
        i = len(shards)
        fx, fy = f"shard_{i:05d}_x.npy", f"shard_{i:05d}_y.npy"
        np.save(os.path.join(root, fx), np.ascontiguousarray(x),
                allow_pickle=False)
        np.save(os.path.join(root, fy), y, allow_pickle=False)
        shards.append({"x": fx, "y": fy, "n": int(n)})
        buf_x[:] = [rest_x] if len(rest_x) else []
        buf_y[:] = [rest_y] if len(rest_y) else []
        buffered = len(rest_x)

    for x, y in batches:
        x, y = np.asarray(x), np.asarray(y)
        if x.dtype != np.uint8:
            raise ValueError(f"images must be uint8, got {x.dtype}")
        if shape is None:
            shape = x.shape[1:]
        elif tuple(x.shape[1:]) != tuple(shape):
            raise ValueError(f"chunk shape {x.shape[1:]} != first {shape}")
        buf_x.append(x)
        buf_y.append(y)
        buffered += len(x)
        while buffered >= samples_per_shard:
            flush(samples_per_shard)
    if buffered:
        flush(buffered)
    index = {
        "shards": shards,
        "shape": list(shape) if shape is not None else [],
        "total": int(sum(s["n"] for s in shards)),
    }
    with open(os.path.join(root, INDEX_FILE), "w") as fp:
        json.dump(index, fp)
    return root


class ShardedImageDataset(Dataset):
    """Memory-mapped sharded image dataset (see module docstring).

    Satisfies the ``Dataset`` protocol plus the Loader's fast
    ``batch(indices)`` path; ``shard_maps``/``shard_starts`` expose the
    mapped segments for the native worker's pointer table."""

    def __init__(self, root: str, transform: Optional[Transform] = None):
        with open(os.path.join(root, INDEX_FILE)) as fp:
            index = json.load(fp)
        codec = index.get("codec", "raw")
        if codec != "raw":
            raise ValueError(
                f"{root!r} holds {codec!r}-codec shards, not raw uint8 "
                "pixel shards; open it with ShardedJpegDataset"
            )
        self.root = root
        self.transform = transform
        self.shape = tuple(index["shape"])
        self.total = int(index["total"])
        # mmap_mode='r': mapping is O(1) — no bytes are read until touched.
        self.shard_maps = [
            np.load(os.path.join(root, s["x"]), mmap_mode="r",
                    allow_pickle=False)
            for s in index["shards"]
        ]
        for m, s in zip(self.shard_maps, index["shards"]):
            if m.dtype != np.uint8 or tuple(m.shape[1:]) != self.shape:
                raise ValueError(
                    f"shard {s['x']}: {m.dtype} {m.shape} does not match "
                    f"index uint8 {self.shape}"
                )
        counts = np.asarray([s["n"] for s in index["shards"]], np.int64)
        # shard_starts[i] = first global index of shard i (+ total sentinel).
        self.shard_starts = np.concatenate([[0], np.cumsum(counts)])
        # Labels are tiny — hold them in RAM as one array.
        self.targets = np.concatenate([
            np.load(os.path.join(root, s["y"]), allow_pickle=False)
            for s in index["shards"]
        ]).astype(np.int32)
        assert len(self.targets) == self.total, (len(self.targets), self.total)

    def __len__(self) -> int:
        return self.total

    def __getitem__(self, idx: int):
        # Python indexing semantics match ArrayDataset — streaming is a
        # residency decision, not a semantics change.
        if idx < 0:
            idx += self.total
        if not 0 <= idx < self.total:
            raise IndexError(
                f"index {idx} out of range for dataset of {self.total}"
            )
        s = int(np.searchsorted(self.shard_starts, idx, "right") - 1)
        return (
            np.asarray(self.shard_maps[s][idx - self.shard_starts[s]]),
            self.targets[idx],
        )

    def batch(self, indices: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Batched gather across the maps — the Loader's hot path.  One
        fancy-index per touched shard; only the touched pages fault in."""
        indices = np.asarray(indices)
        out = np.empty((len(indices),) + self.shape, np.uint8)
        shard_of = np.searchsorted(self.shard_starts, indices, "right") - 1
        for s in np.unique(shard_of):
            rows = shard_of == s
            out[rows] = self.shard_maps[s][indices[rows] - self.shard_starts[s]]
        return out, self.targets[indices]


def write_sharded_jpeg_dataset(
    root: str,
    samples: "Iterable[Tuple[bytes, int]]",
    shape: Tuple[int, int, int],
    samples_per_shard: int = 8192,
) -> str:
    """Write (jpeg_bytes, label) samples as COMPRESSED shards: each shard
    is one ``.bin`` of concatenated baseline-JPEG streams plus an
    ``[n+1]`` int64 offset table — the dataset stays at ~source size on
    disk (raw uint8 shards cost ~13x for ImageNet-class inputs), and the
    C++ worker decodes per sample on its threads
    (csrc/jpeg_decoder.cpp).  ``shape`` is the (H, W, C) every stream
    must decode to (the worker validates per image)."""
    os.makedirs(root, exist_ok=True)
    shards = []
    buf: list = []
    labels: list = []

    def flush():
        i = len(shards)
        fj, fo = f"shard_{i:05d}_j.bin", f"shard_{i:05d}_o.npy"
        fy = f"shard_{i:05d}_y.npy"
        offsets = np.zeros(len(buf) + 1, np.int64)
        np.cumsum([len(b) for b in buf], out=offsets[1:])
        with open(os.path.join(root, fj), "wb") as fp:
            for b in buf:
                fp.write(b)
        np.save(os.path.join(root, fo), offsets, allow_pickle=False)
        np.save(os.path.join(root, fy), np.asarray(labels, np.int32),
                allow_pickle=False)
        shards.append({"j": fj, "o": fo, "y": fy, "n": len(buf)})
        buf.clear()
        labels.clear()

    for data, label in samples:
        buf.append(bytes(data))
        labels.append(int(label))
        if len(buf) >= samples_per_shard:
            flush()
    if buf:
        flush()
    index = {
        "codec": "jpeg",
        "shards": shards,
        "shape": list(shape),
        "total": int(sum(s["n"] for s in shards)),
    }
    with open(os.path.join(root, INDEX_FILE), "w") as fp:
        json.dump(index, fp)
    return root


def encode_jpeg_samples(
    batches: Iterable[Tuple[np.ndarray, np.ndarray]],
    quality: int = 88,
    subsampling: int = 0,
):
    """(images [n,H,W,C] uint8, labels) chunks -> (jpeg_bytes, label)
    samples for ``write_sharded_jpeg_dataset``.  ``subsampling=0``
    (4:4:4) is the default: no chroma upsampling at decode, so the
    native decoder matches libjpeg to IDCT rounding (±3); 2 (4:2:0)
    halves the size again and decodes through the triangular upsampler."""
    import io

    from PIL import Image

    for x, y in batches:
        x = np.asarray(x)
        if x.dtype != np.uint8:
            raise ValueError(f"images must be uint8, got {x.dtype}")
        for img, label in zip(x, y):
            buf = io.BytesIO()
            Image.fromarray(img).save(
                buf, "JPEG", quality=quality, subsampling=subsampling
            )
            yield buf.getvalue(), int(label)


class ShardedJpegDataset(Dataset):
    """Compressed sharded dataset (``write_sharded_jpeg_dataset``
    layout): JPEG byte streams memory-mapped per shard, offset tables
    and labels in RAM.

    ``__getitem__``/``batch`` decode through the native decoder
    (csrc/jpeg_decoder.cpp) so the Python path and the C++ worker
    produce BIT-EQUAL pixels; PIL is the fallback when the native
    library is unavailable (same images to ±3 — IDCT rounding)."""

    def __init__(self, root: str, transform: Optional[Transform] = None):
        with open(os.path.join(root, INDEX_FILE)) as fp:
            index = json.load(fp)
        if index.get("codec") != "jpeg":
            raise ValueError(
                f"{root!r} is not a jpeg-sharded dataset "
                f"(codec={index.get('codec')!r}); use ShardedImageDataset"
            )
        self.root = root
        self.transform = transform
        self.shape = tuple(index["shape"])
        self.total = int(index["total"])
        self.byte_maps = [
            np.memmap(os.path.join(root, s["j"]), np.uint8, "r")
            for s in index["shards"]
        ]
        self.offset_tables = [
            np.load(os.path.join(root, s["o"]), allow_pickle=False)
            for s in index["shards"]
        ]
        for m, o, s in zip(self.byte_maps, self.offset_tables,
                           index["shards"]):
            if len(o) != s["n"] + 1 or o[-1] != len(m):
                raise ValueError(f"shard {s['j']}: offset table mismatch")
        counts = np.asarray([s["n"] for s in index["shards"]], np.int64)
        self.shard_starts = np.concatenate([[0], np.cumsum(counts)])
        self.targets = np.concatenate([
            np.load(os.path.join(root, s["y"]), allow_pickle=False)
            for s in index["shards"]
        ]).astype(np.int32)
        assert len(self.targets) == self.total

    def __len__(self) -> int:
        return self.total

    def _decode(self, data: np.ndarray) -> np.ndarray:
        from ml_trainer_tpu.data.native import jpeg_decode_np

        out = jpeg_decode_np(data, self.shape)
        if out is not None:
            return out
        import io

        from PIL import Image

        return np.asarray(
            Image.open(io.BytesIO(data.tobytes())).convert("RGB")
        )

    def __getitem__(self, idx: int):
        if idx < 0:
            idx += self.total
        if not 0 <= idx < self.total:
            raise IndexError(
                f"index {idx} out of range for dataset of {self.total}"
            )
        s = int(np.searchsorted(self.shard_starts, idx, "right") - 1)
        local = idx - self.shard_starts[s]
        o = self.offset_tables[s]
        return (
            self._decode(self.byte_maps[s][o[local]:o[local + 1]]),
            self.targets[idx],
        )

    def batch(self, indices: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        indices = np.asarray(indices)
        out = np.empty((len(indices),) + self.shape, np.uint8)
        for i, idx in enumerate(indices):
            out[i] = self[int(idx)][0]
        return out, self.targets[indices]


def ingest_image_folder(
    src: str,
    dst: str,
    size: Tuple[int, int] = (224, 224),
    samples_per_shard: int = 4096,
    extensions: Tuple[str, ...] = (".jpg", ".jpeg", ".png", ".bmp"),
    decode_batch: int = 256,
    codec: str = "raw",
    quality: int = 88,
    subsampling: int = 0,
) -> str:
    """Decode a torchvision-``ImageFolder``-layout directory
    (``src/<class_name>/*.jpg``, classes labeled by sorted name) into the
    sharded on-disk format — the ImageNet ingestion path.

    ``codec='raw'`` (default — the original on-disk format; existing
    callers keep opening results with ``ShardedImageDataset``) writes
    uint8 pixel shards (~13x larger than source for ImageNet-class
    inputs).  ``codec='jpeg'`` opts into compressed shards: the resized
    images re-encode as baseline JPEG (~source size on disk; the C++
    worker decodes per sample — open with ``ShardedJpegDataset``).

    Decoding streams: ``decode_batch`` images are decoded (PIL), resized
    to ``size`` and handed to the sharded writer at a time, so peak RAM
    is one shard regardless of dataset size.  Returns ``dst``."""
    if codec not in ("raw", "jpeg"):
        raise ValueError(f"codec must be 'raw' or 'jpeg', got {codec!r}")
    from PIL import Image

    classes = sorted(
        d for d in os.listdir(src)
        if os.path.isdir(os.path.join(src, d))
    )
    if not classes:
        raise ValueError(f"no class directories under {src!r}")
    files = [
        (os.path.join(src, c, f), label)
        for label, c in enumerate(classes)
        for f in sorted(os.listdir(os.path.join(src, c)))
        if f.lower().endswith(extensions)
    ]
    if not files:
        raise ValueError(f"no image files under {src!r}")

    def chunks():
        for lo in range(0, len(files), decode_batch):
            part = files[lo : lo + decode_batch]
            xs = np.empty((len(part),) + size + (3,), np.uint8)
            ys = np.empty((len(part),), np.int32)
            for i, (path, label) in enumerate(part):
                with Image.open(path) as im:
                    xs[i] = np.asarray(
                        im.convert("RGB").resize(
                            (size[1], size[0]), Image.BILINEAR
                        )
                    )
                ys[i] = label
            yield xs, ys

    if codec == "jpeg":
        write_sharded_jpeg_dataset(
            dst,
            encode_jpeg_samples(chunks(), quality, subsampling),
            shape=size + (3,),
            samples_per_shard=samples_per_shard,
        )
    else:
        write_sharded_dataset(
            dst, chunks(), samples_per_shard=samples_per_shard
        )
    with open(os.path.join(dst, INDEX_FILE)) as fp:
        index = json.load(fp)
    index["classes"] = classes
    with open(os.path.join(dst, INDEX_FILE), "w") as fp:
        json.dump(index, fp)
    return dst
