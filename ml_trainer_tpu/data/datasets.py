"""Datasets: array-backed containers with optional batch transforms.

The reference consumes ``torchvision.datasets.CIFAR10`` objects
(ref: main.py:14-28).  Here the canonical container is ``ArrayDataset`` —
contiguous numpy arrays, which is what a TPU input pipeline wants (batch
assembly is a slice, not a Python-object gather).  ``as_dataset`` adapts
reference-style torch datasets so the 01/02/03 notebook flow still works.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional, Tuple

import numpy as np

from ml_trainer_tpu.data.transforms import Transform


class Dataset:
    """Minimal dataset protocol: ``len`` and integer indexing -> (x, y)."""

    transform: Optional[Transform] = None

    def __len__(self) -> int:
        raise NotImplementedError

    def __getitem__(self, idx: int) -> Tuple[Any, Any]:
        raise NotImplementedError


class ArrayDataset(Dataset):
    """Dataset over in-memory numpy arrays with an optional *batched*
    transform (applied by the Loader per batch, not per sample)."""

    def __init__(
        self,
        data: np.ndarray,
        targets: np.ndarray,
        transform: Optional[Transform] = None,
    ):
        assert len(data) == len(targets), (len(data), len(targets))
        self.data = np.asarray(data)
        self.targets = np.asarray(targets)
        self.transform = transform

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        return self.data[idx], self.targets[idx]

    def batch(self, indices: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Fast batched gather — the Loader's hot path."""
        return self.data[indices], self.targets[indices]


class CIFAR10(ArrayDataset):
    """CIFAR-10 from the standard ``cifar-10-batches-py`` pickle layout on
    disk (the same files torchvision unpacks; ref: main.py:14-28 uses
    ``download=False`` too, so on-disk data is the reference contract as
    well).  Images are stored NHWC uint8; transforms run per batch."""

    def __init__(self, root: str, train: bool = True, transform=None):
        base = os.path.join(root, "cifar-10-batches-py")
        if not os.path.isdir(base):
            raise FileNotFoundError(
                f"CIFAR-10 pickle batches not found under {base!r}. "
                "Place the extracted 'cifar-10-batches-py' directory there "
                "(no download is attempted), or use SyntheticCIFAR10 for "
                "smoke tests and benchmarks."
            )
        files = (
            [f"data_batch_{i}" for i in range(1, 6)] if train else ["test_batch"]
        )
        xs, ys = [], []
        for name in files:
            with open(os.path.join(base, name), "rb") as fp:
                entry = pickle.load(fp, encoding="latin1")
            xs.append(entry["data"])
            ys.extend(entry["labels"])
        data = (
            np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        )  # NCHW-packed file -> NHWC
        super().__init__(data, np.asarray(ys, dtype=np.int32), transform)


class SyntheticCIFAR10(ArrayDataset):
    """Deterministic CIFAR-10-shaped random data for tests and benchmarks
    (stands in for the real dataset in the zero-egress environment)."""

    def __init__(self, size: int = 1024, transform=None, seed: int = 0):
        rng = np.random.default_rng(seed)
        data = rng.integers(0, 256, size=(size, 32, 32, 3), dtype=np.uint8)
        targets = rng.integers(0, 10, size=(size,)).astype(np.int32)
        super().__init__(data, targets, transform)


class SyntheticTokens(ArrayDataset):
    """Deterministic token-id dataset for LM / encoder smoke tests
    (the tokenized-dataset path of the BERT/GPT-2 north-star configs)."""

    def __init__(
        self,
        size: int = 256,
        seq_len: int = 128,
        vocab_size: int = 1024,
        num_classes: Optional[int] = None,
        seed: int = 0,
    ):
        rng = np.random.default_rng(seed)
        data = rng.integers(0, vocab_size, size=(size, seq_len)).astype(np.int32)
        if num_classes is None:
            # Causal LM: target is the next token.
            targets = np.roll(data, -1, axis=1)
        else:
            targets = rng.integers(0, num_classes, size=(size,)).astype(np.int32)
        super().__init__(data, targets, None)


def as_dataset(ds: Any) -> Dataset:
    """Adapt foreign datasets (e.g. torchvision CIFAR10 passed by
    reference-style notebooks) into an ``ArrayDataset``."""
    if isinstance(ds, Dataset):
        return ds
    if hasattr(ds, "data") and hasattr(ds, "targets"):
        from ml_trainer_tpu.data.transforms import ForeignTransform, Transform

        data = np.asarray(ds.data)
        if data.ndim == 4 and data.shape[1] in (1, 3) and data.shape[-1] not in (1, 3):
            data = data.transpose(0, 2, 3, 1)  # NCHW -> NHWC
        transform = getattr(ds, "transform", None)
        if transform is not None and not isinstance(transform, Transform):
            # Foreign per-sample transform (torchvision Compose from the
            # reference notebooks) — adapt to the batched calling convention.
            transform = ForeignTransform(transform)
        return ArrayDataset(data, np.asarray(ds.targets), transform)
    # Fall back to item-by-item materialization.
    xs, ys = zip(*[ds[i] for i in range(len(ds))])
    return ArrayDataset(np.stack([np.asarray(x) for x in xs]), np.asarray(ys))
