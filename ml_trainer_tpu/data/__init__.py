"""Host-side input pipeline: datasets, batch transforms, sharded sampling and
a prefetching ``Loader`` — the TPU-native stand-in for torch's DataLoader +
DistributedSampler stack (ref: src/dataloader.py:5, src/trainer.py:60-64,
77-79)."""

from ml_trainer_tpu.data.datasets import (
    ArrayDataset,
    CIFAR10,
    Dataset,
    SyntheticCIFAR10,
    SyntheticTokens,
    as_dataset,
)
from ml_trainer_tpu.data.loader import Loader, prefetch_to_device
from ml_trainer_tpu.data.sampler import ShardedSampler
from ml_trainer_tpu.data.sharded import (
    ShardedImageDataset,
    ingest_image_folder,
    write_sharded_dataset,
)
from ml_trainer_tpu.data.text import (
    PackedLMDataset,
    TokenizedDataset,
    load_sst2_tsv,
    pack_texts,
    tokenize_texts,
)
from ml_trainer_tpu.data.tokenizers import (
    ByteLevelBPETokenizer,
    WordPieceTokenizer,
    load_tokenizer,
)
from ml_trainer_tpu.data.transforms import (
    Compose,
    Normalize,
    RandomCrop,
    RandomHorizontalFlip,
    ToFloat,
)

__all__ = [
    "ArrayDataset",
    "CIFAR10",
    "Dataset",
    "SyntheticCIFAR10",
    "SyntheticTokens",
    "as_dataset",
    "Loader",
    "prefetch_to_device",
    "ShardedSampler",
    "ShardedImageDataset",
    "ingest_image_folder",
    "write_sharded_dataset",
    "PackedLMDataset",
    "TokenizedDataset",
    "load_sst2_tsv",
    "pack_texts",
    "tokenize_texts",
    "ByteLevelBPETokenizer",
    "WordPieceTokenizer",
    "load_tokenizer",
    "Compose",
    "Normalize",
    "RandomCrop",
    "RandomHorizontalFlip",
    "ToFloat",
]
