"""Trainer — the config-driven fit/validate/test orchestrator, mesh-native.

Public surface parity with the reference ``Trainer``
(ref: src/trainer.py:22-311): same constructor signature
``Trainer(model, datasets, epochs, batch_size, is_parallel, save_history,
**config)`` with the same eleven whitelisted config keys, the same
``fit()`` / ``test()`` / ``save_model()`` / ``clear()`` /
``validate_kwargs()`` methods, the same history schema
(ref: src/trainer.py:265-272), per-epoch host-0 model saving
(ref: src/trainer.py:252-256) and the dataset-less "testing only" mode
(ref: src/trainer.py:66-71, 03 nb cell-7).

TPU-native internals (the deliberate re-design, SURVEY.md §7):

* the train step is ONE compiled XLA program — forward, loss, backward,
  gradient all-reduce and optimizer update fused by ``jax.jit`` under a
  device mesh.  The reference's per-batch ``loss.item()`` sync and host-side
  sklearn metric (ref: src/trainer.py:186, 164-166) are replaced by
  on-device accumulators fetched once per epoch;
* data parallelism is a sharding annotation, not a module wrapper: batches
  are placed with a ``NamedSharding`` over the mesh's data axis and XLA
  inserts the gradient psum — the DDP + SMDDP stack collapses into the
  compiler (ref: src/trainer.py:97-101, 43-44);
* LR schedules are functions of the on-device step counter (the host-side
  ``scheduler.step()`` calls of ref: src/trainer.py:189-199 would force
  syncs); ReduceLROnPlateau runs host-side at epoch boundaries — and
  actually steps, unlike the reference's dead instance (documented fix);
* checkpoints carry full training state and ``fit(resume=True)`` restarts
  from the latest epoch — the reference is save-only (SURVEY.md §5).
"""

from __future__ import annotations

import gc
import math
import os
import time
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P
from tqdm import tqdm

from ml_trainer_tpu import checkpoint as ckpt
from ml_trainer_tpu.config import TrainerConfig, validate_kwargs
from ml_trainer_tpu.data import Loader, ShardedSampler, prefetch_to_device
from ml_trainer_tpu.models.registry import get_model
from ml_trainer_tpu.ops import (
    decay_mask_matrices_only,
    get_criterion,
    get_metric,
    get_optimizer,
    get_prediction_function,
    make_lr_schedule,
    PlateauController,
)
from ml_trainer_tpu.parallel import (
    batch_sharding,
    create_mesh,
    fit_sharding_to_rank,
    replicated,
)
from ml_trainer_tpu.parallel.distributed import (
    initialize_distributed,
    is_primary,
    process_count,
    process_index,
)
from ml_trainer_tpu.train_state import TrainState
from ml_trainer_tpu.utils.logging import get_logger
from ml_trainer_tpu.utils.utils import LoadedModel

logger = get_logger("ml_trainer_tpu.trainer")

# Set when a Trainer(backend='cpu') pinned the host platform: the pin is
# process-wide and irreversible once the backend initializes, so a later
# Trainer(backend='tpu') in the same process must be told it is NOT on
# the chip (jax gives it the CPU backend with no error of its own).
_CPU_PLATFORM_PINNED = False


def enable_compilation_cache(path: str = "/tmp/ml_trainer_tpu_jax_cache") -> None:
    """Persistent XLA compilation cache, shared across processes.

    The first compile of a big model costs minutes; without this every new
    CLI invocation pays it again (torch has no analog cost — XLA does, so
    the framework owns mitigating it).  Idempotent, best-effort.

    Verified to work under the remote-compile PJRT tunnel too (round-2
    probe: cached re-run of a jit cut 1.9s -> 0.3s, cache entries written,
    no client wedge), so it is no longer disabled there; set
    ``ML_TRAINER_TPU_NO_COMPILE_CACHE=1`` to opt out.

    CPU-pinned runs (tests, the dev fallback) skip the cache entirely:
    its whole point is amortizing minutes-long TPU compiles, CPU compiles
    are fast — and jaxlib 0.4.36's CPU client mishandles buffer donation
    in executables reloaded from the persistent cache (reloading a
    donated train step intermittently corrupts the process heap; found
    by the resilience chaos matrix, reproduced 4/5 with the cache warm
    and 0/5 with it off)."""
    if os.environ.get("ML_TRAINER_TPU_NO_COMPILE_CACHE") == "1":
        return
    platforms = (
        os.environ.get("JAX_PLATFORMS")
        or str(getattr(jax.config, "jax_platforms", None) or "")
    )
    if platforms.strip().lower() == "cpu":
        return
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # older/newer jax without these flags: skip silently
        pass


def _chunk_batches(loader, k: int, tail: list):
    """Yield [K, B, ...] stacks of full batches; ragged batches (and the
    final partial chunk) land in ``tail`` once the generator drains."""
    xs, ys = [], []
    full = None  # leading dim of a full batch (first seen)
    for x, y in loader:
        if full is None:
            full = x.shape[0]
        if x.shape[0] != full:
            # Ragged final batch (drop_last=False): un-stackable, so it
            # always goes through the per-batch tail path even when it
            # would land inside a full chunk.
            tail.append((x, y))
            continue
        xs.append(x)
        ys.append(y)
        if len(xs) == k:
            yield np.stack(xs), np.stack(ys)
            xs, ys = [], []
    tail.extend(zip(xs, ys))


def _module_takes_train(module) -> bool:
    import inspect

    try:
        return "train" in inspect.signature(module.__call__).parameters
    except (TypeError, ValueError):
        return False


def _module_takes_targets(module) -> bool:
    """Models that accept ``targets`` compute their own loss (e.g. GPT2's
    chunked LM head, which never materializes the logits tensor); the
    Trainer then feeds labels through the forward instead of applying the
    criterion to returned logits."""
    import inspect

    try:
        params = inspect.signature(module.__call__).parameters
    except (TypeError, ValueError):
        return False
    # Only engage for models that OPT IN to the self-loss path: accepting
    # the argument is not enough (a model might take targets for teacher
    # forcing and still return logits) — it must carry an active
    # ``loss_chunk`` attribute (GPT2: loss_chunk > 0).
    return "targets" in params and bool(getattr(module, "loss_chunk", 0))


class Trainer:
    def __init__(
        self,
        model,
        datasets=None,
        epochs: Optional[int] = None,
        batch_size: Optional[int] = None,
        is_parallel: bool = False,
        save_history: bool = False,
        mesh_shape: Optional[dict] = None,
        sharding_rules=None,
        grad_accum_steps: int = 1,
        loader: str = "auto",
        steps_per_execution: int = 1,
        shard_opt_state: bool = False,
        grad_clip_norm: Optional[float] = None,
        ema_decay: Optional[float] = None,
        moe_aux_weight: float = 0.01,
        early_stop_patience: Optional[int] = None,
        save_best: bool = False,
        decay_exclude_bias_norm: bool = False,
        label_smoothing: float = 0.0,
        sharded_checkpoint: Optional[bool] = None,
        nonfinite_guard: bool = True,
        rollback_bad_steps: Optional[int] = None,
        rollback_lr_backoff: float = 0.5,
        save_every_steps: Optional[int] = None,
        handle_preemption: bool = True,
        telemetry: bool = False,
        log_every_steps: Optional[int] = None,
        desync_every_steps: Optional[int] = None,
        straggler_factor: float = 2.0,
        precision: Any = None,
        loss_scale: Any = "dynamic",
        dp_update: str = "fused",
        fused_adam: Optional[bool] = None,
        bucket_mb: float = 4.0,
        pipeline_schedule: Optional[str] = None,
        elastic: Any = None,
        lora: Any = None,
        **config: Any,
    ):
        """``mesh_shape`` / ``sharding_rules`` are TPU-native extensions
        beyond the reference's DP-only surface (SURVEY.md §2C): e.g.
        ``mesh_shape={'data': 4, 'tensor': 2}`` with
        ``sharding_rules=parallel.tp_rules.TRANSFORMER_TP_RULES`` trains
        tensor-parallel; both default to pure data parallelism.

        ``grad_accum_steps`` splits each global batch into that many
        microbatches inside the compiled step (a ``lax.scan`` over gradient
        accumulation, one optimizer update per batch) — the GPT-2 north-star
        requirement (BASELINE.json configs[4]); effective batch semantics
        and the LR schedule's step count are unchanged.

        ``loader``: 'auto' (default) assembles batches through the C++
        NativeLoader (csrc/batch_worker.cpp — the torch DataLoader
        worker-pool role, SURVEY.md §2B) whenever the dataset+transform can
        run the fused native pipeline with identical semantics, else the
        Python Loader; 'native' requires it (raises if unsupported);
        'python' forces the Python path.

        ``steps_per_execution``: run that many optimizer steps per device
        dispatch (a ``lax.scan`` over stacked batches inside ONE compiled
        program).  The update sequence, PRNG stream, LR schedule, and
        history are bit-identical to ``steps_per_execution=1``; only the
        per-step Python/dispatch overhead is amortized — the lever that
        matters for small models, where the reference pays a full
        host round-trip per batch (ref: src/trainer.py:186).

        ``shard_opt_state``: ZeRO-1-style placement — replicated optimizer
        moments are partitioned over the ``data`` mesh axis (a sharding
        annotation; XLA inserts the implied collectives), cutting optimizer
        memory per device by the data-parallel degree with an identical
        update sequence.

        ``grad_clip_norm``: clip gradients to this global L2 norm before
        the optimizer update (``optax.clip_by_global_norm`` chained in
        front of the optimizer — with grad accumulation the clip applies
        to the averaged global-batch gradient, matching torch's
        ``clip_grad_norm_``-before-``step()`` placement).

        ``ema_decay``: maintain an exponential moving average of the
        parameters on-device (``ema = d*ema + (1-d)*params`` each step).
        When set, validation, ``test()`` and ``save_model`` use the EMA
        weights (the standard ViT/ImageNet recipe); the raw weights keep
        training and are what checkpoints resume from (both live in the
        checkpointed TrainState).

        ``moe_aux_weight``: coefficient on auxiliary losses the model sows
        into the ``losses`` collection (the Switch-Transformer load-balance
        loss from ``models.moe.MoEMLP``).  Captured inside the compiled
        train step and added to the training loss, so top-1 routing is
        actually pushed toward balanced expert assignment; dense models sow
        nothing and pay nothing.

        ``early_stop_patience``: stop ``fit()`` after this many epochs
        without a new best validation loss (the best/bad-epoch counters
        live in checkpoints, so a resumed run keeps counting).  ``None``
        (default) trains the full epoch budget like the reference.

        ``save_best``: additionally export the weights to
        ``<model_dir>/best`` whenever validation loss improves — the
        every-epoch save overwrites with the LAST weights (ref behavior);
        this keeps the best ones too.

        ``decay_exclude_bias_norm``: apply weight decay to matrices only
        (ndim >= 2), skipping biases and LayerNorm params — the standard
        transformer recipe.  Default False = torch/reference semantics
        (decay everything).

        ``label_smoothing``: mix each one-hot target with the uniform
        distribution at this weight (torch's
        ``CrossEntropyLoss(label_smoothing=...)``; the ViT/ResNet
        recipe).  Only valid with ``criterion='cross_entropy'``.

        ``sharded_checkpoint``: write full-state checkpoints in the
        per-host sharded format — every process saves exactly its
        addressable shards (ZeRO-1 moments, TP/FSDP params) instead of
        host 0 allgathering the full tree.  Restore stitches shards back
        per-device, including onto a DIFFERENT mesh/device count than the
        one that saved (elastic resume after preemption).  Requires the
        model_dir to be storage shared by all hosts.  Default ``None`` =
        auto: on whenever the run is multi-process AND the state has
        genuinely partitioned leaves — the combination where a host-0
        full-tree gather is not just a RAM spike but a deadlock (one
        process launching a global allgather the others never join).
        The reference's rank-0 save (ref: src/trainer.py:252-254)
        generalized to sharded state.

        Resilience knobs (docs/resilience.md):

        ``nonfinite_guard`` (default True): the compiled train step
        checks loss and every gradient leaf for finiteness ON-DEVICE and
        ``where``-selects the previous state when the check fails — the
        bad step is skipped with no recompilation and no host sync, the
        skipped/streak counters live in ``TrainState`` (fetched once per
        epoch into ``history['skipped_steps']``).  With all-finite math
        the trajectory is bit-identical to the unguarded step.

        ``rollback_bad_steps``: after this many CONSECUTIVE skipped
        steps, restore the newest checkpoint that verifies (corrupt ones
        are quarantined) and scale the LR by ``rollback_lr_backoff``
        (compounding per rollback) — the escape hatch for a diverged
        run that keeps producing NaNs from poisoned state.  Checked at
        the existing ``log_every`` sync points, so it adds no extra
        per-step host sync.  ``None`` (default) disables rollback.

        ``save_every_steps``: additionally checkpoint every N optimizer
        steps WITHIN an epoch, with the batch cursor and epoch
        accumulators in the manifest, so ``fit(resume=True)`` restarts
        mid-epoch bit-exactly (the resumed trajectory equals the
        uninterrupted one).  Requires ``steps_per_execution=1`` (the
        per-batch dispatch path owns the step cursor).

        ``telemetry`` (default False): training step telemetry
        (docs/observability.md) — grad-norm / param-norm / update-ratio
        stats computed ON-DEVICE inside the compiled train step (pure
        extra outputs: no host sync, no extra compiled programs, and the
        update trajectory is untouched), fetched at the existing
        ``log_every`` sync cadence and emitted as structured
        ``train_step_telemetry`` events, registry gauges
        (``telemetry.default_registry()``), and flight-recorder step
        records — plus samples/s, tokens/s and an analytic MFU estimate
        (``telemetry/flops.py``, TPU backend only).  Also arms the
        third observability pillar: the analytic per-device HBM ledger
        published as ``mem_*`` gauges with a live cross-check
        (``telemetry/memory.py``), goodput accounting — per-run
        wall-clock decomposed into data-wait / h2d / ckpt-stall /
        compile / rollback / preempt-gap buckets behind a
        ``train_goodput_fraction`` gauge (``telemetry/goodput.py``) —
        and recompile forensics (``telemetry/compile_watch.py``:
        ``compile_events_total{fn=}``, flight ``recompile`` events
        naming the offending shape after the first epoch closes
        warmup); flight dumps attach the device-memory snapshot and
        recent compile events.

        ``log_every_steps``: override the host-sync cadence (default 50
        steps) — the progress-bar fetch, rollback check, and telemetry
        emission all ride this clock, so lowering it trades throughput
        for observability granularity.

        ``desync_every_steps``: additionally run the cross-host
        replica-desync check every N optimizer steps (default None =
        epoch boundaries only, the PR-3 behavior).  Each check costs one
        scalar broadcast over DCN plus the local fingerprint fetch; on
        mismatch the diverging host records + dumps a flight event
        naming itself and the step before raising
        (``parallel/desync.py``).  No-op single-process.

        ``straggler_factor``: with ``telemetry=True``, a host whose
        fenced step-time p50 exceeds the cluster (lower-)median by this
        factor at an aggregation point fires
        ``cluster_straggler_events_total{host=...}`` and a flight event
        (``telemetry/cluster.py``; heartbeats allgather at epoch
        boundaries).  Must be > 1.

        Mixed precision / data-parallel hot path (docs/mixed_precision.md):

        ``precision``: ``None``/``'fp32'`` (default — the exact
        pre-policy program, bit-identical trajectory) or ``'bf16'`` / a
        ``precision.Precision`` — forward/backward compute in bf16
        against the fp32 master params in ``TrainState`` (cast once at
        the top of the loss function; the criterion and metrics read
        fp32 outputs).  Transformer-family modules additionally get
        their ``dtype`` knob set so module-internal casts agree.

        ``loss_scale`` (only with an active bf16 policy): ``'dynamic'``
        (default) scales the loss before backward and unscales the
        gradients, halving the scale on a non-finite step WITHOUT
        advancing the rollback streak (overflow is the scale's fault
        until it has backed off to its floor) and doubling it after
        ``GROWTH_INTERVAL`` consecutive finite steps; a float pins a
        static scale; ``None`` disables scaling (bare bf16).  Requires
        ``nonfinite_guard`` — the skip machinery is the backoff path.
        The scale and its growth counter live in ``TrainState``
        (``loss_scale`` / ``good_steps``), maintained on-device.

        ``dp_update``: ``'fused'`` (default) keeps the single implicit
        gradient psum XLA inserts behind the batch sharding and the
        replicated weight update.  ``'sharded'`` rewrites the pure-DP
        hot path per arXiv 2004.13336: gradients leave the backward
        through size-bounded per-bucket ``reduce_scatter`` collectives
        (reverse topological order, so each bucket's communication can
        hide under remaining backward compute), each replica applies the
        optimizer update only to its 1/N shard of grads/params/moments
        (ZeRO-1 moments are implied and forced on), and fresh weights
        return via bucketed ``all_gather`` — update FLOPs and optimizer
        memory drop by the data-parallel degree with the same math
        (trajectory-equality test-pinned).  Requires a pure-DP mesh
        (only a live ``data`` axis), no sharding_rules, no batch_stats
        models, and ``steps_per_execution=1``.

        ``bucket_mb``: reduce-scatter bucket size bound in MiB for the
        sharded path (default 4) — smaller buckets start communicating
        earlier but pay more per-collective latency.

        ``pipeline_schedule``: override the pipeline-parallel schedule of
        a pipelined model (``'gpipe'`` | ``'1f1b'`` | ``'interleaved'``
        | ``'zb'`` — ``parallel.pipeline.SCHEDULES``; docs/pipeline.md).
        The model must carry a ``schedule`` knob (``GPT2Pipelined``); it
        is cloned with the override, exactly like the precision dtype
        threading.  All schedules compute the same math — trajectories
        are schedule-invariant (test-pinned) — so this knob only moves
        WHERE/WHEN stage work runs: 1F1B bounds the activation stash,
        interleaved shrinks the bubble by the model's ``n_virtual``.
        ``None`` (default) keeps the model's own setting.

        ``handle_preemption`` (default True): ``fit()`` installs
        SIGTERM/SIGINT handlers (restored on exit) that finish the
        in-flight step, write an emergency mid-epoch checkpoint plus a
        clean-exit marker, and return with ``self.preempted = True`` —
        the preemptible-TPU contract.  ``fit(resume=True)`` picks the
        marker up and continues where the signal landed.

        ``elastic`` (docs/resilience.md "Elastic"): an int simulated
        host count or a ``resilience.elastic.ElasticConfig``.  The mesh
        decomposes into N equal host groups (contiguous blocks of data
        replicas); a ``host_kill``/``host_hang`` fault or a straggler
        verdict from ``telemetry/cluster.py`` whose factor reaches
        ``straggler_reshape_factor`` then drains the in-flight step,
        writes the emergency checkpoint, drops the lost host's devices,
        re-places the state in ONE ``place_tree`` program, rescales
        global batch / LR per ``batch_policy``, and continues the SAME
        ``fit()`` call — each event recorded in ``history['reshapes']``,
        a flight ``reshape`` event and the goodput ``reshape`` bucket.
        Single-process (simulated cluster) only: a real multi-process
        pod cannot reshape its process set in place, so there the same
        faults drive the drain→checkpoint→restart path and the
        topology-flexible restore continues the job at the new shape.
        Requires ``steps_per_execution=1`` (the drain needs the
        per-batch cursor).

        ``lora`` (docs/serving.md "Batched LoRA adapters"): a
        :class:`~ml_trainer_tpu.lora.LoraConfig` (or its kwargs dict)
        — the model clones with trainable low-rank A/B params on the
        targeted projections (B zero-init, so step 0 IS the base
        model), the BASE weights freeze through an optax
        ``multi_transform`` mask (frozen leaves carry no optimizer
        state, so optimizer memory divides by the frozen fraction —
        the memory ledger shows it), and ``export_lora(path)`` writes
        the adapter artifact the serving engine hot-loads.  Requires a
        model carrying the ``lora_*`` knobs (the GPT-2 family) and
        ``dp_update='fused'``."""
        logger.info("Config inputs.", config=config)
        cfg = TrainerConfig.from_kwargs(**config)
        self.config = cfg
        if cfg.backend == "cpu":
            # The gloo-analog host fallback (ref: main.py:73) must actually
            # select the host platform: environments that pin a TPU platform
            # at interpreter startup (sitecustomize) would otherwise dial
            # the chip for a run the user explicitly routed to CPU.  The
            # update only takes effect if the backend has not initialized
            # yet (it does NOT raise afterwards), so verify the platform
            # that actually came up and surface a silent no-op.
            global _CPU_PLATFORM_PINNED
            prev = getattr(jax.config, "jax_platforms", None)
            jax.config.update("jax_platforms", "cpu")
            if jax.default_backend() != "cpu":
                logger.warning(
                    "backend='cpu' requested after the JAX backend "
                    f"initialized; keeping '{jax.default_backend()}'."
                )
            elif prev != "cpu":
                # Only remember pins that actually changed the platform
                # selection: when the process was already pinned to CPU
                # (tests, CPU-only hosts pinning it themselves) a later
                # backend='tpu' Trainer should not be blamed for it.
                _CPU_PLATFORM_PINNED = True
        elif _CPU_PLATFORM_PINNED:
            # Don't force backend init just to check — the flag already
            # proves a cpu pin took effect earlier in this process.
            logger.warning(
                f"backend='{cfg.backend}' requested, but an earlier "
                "Trainer(backend='cpu') pinned the host platform for this "
                "process; this run will execute on CPU."
            )
        # After the backend pin, so a backend='cpu' run is seen as CPU by
        # the cache gate (CPU runs skip the persistent cache — see
        # enable_compilation_cache).
        enable_compilation_cache()
        # Parity attribute names (ref: src/trainer.py:30-41).
        self.epochs = epochs
        self.scheduler_type = cfg.scheduler
        self.optimizer_type = cfg.optimizer
        self.momentum = cfg.momentum
        self.weight_decay = cfg.weight_decay
        self.lr = cfg.lr
        self.criterion_type = cfg.criterion
        self.metric = cfg.metric
        self.pred_function_type = cfg.pred_function
        self.model_dir = cfg.model_dir
        self.is_parallel = is_parallel
        self.save_history = save_history

        self.train_losses: list = []
        self.val_losses: list = []
        self.train_metrics: list = []
        self.val_metrics: list = []
        self.history: dict = {}
        # Host-sync cadence for progress-bar postfix updates.  The reference
        # fetches the loss every batch (ref: src/trainer.py:186) — a per-step
        # device sync we only pay every `log_every` steps.
        self.log_every = 50

        from ml_trainer_tpu.precision import (
            resolve_loss_scale,
            resolve_precision,
        )

        self.precision = resolve_precision(precision)
        self._compute_dtype = (
            self.precision.compute if self.precision.active else None
        )
        self._loss_scale_cfg = resolve_loss_scale(loss_scale, self.precision)
        if self._loss_scale_cfg is not None and not nonfinite_guard:
            raise ValueError(
                "loss scaling rides the non-finite guard (overflow steps "
                "are skipped by the same where-selects); pass "
                "loss_scale=None to run bare bf16 with nonfinite_guard "
                "disabled"
            )
        if dp_update not in ("fused", "sharded"):
            raise ValueError(
                f"dp_update must be 'fused' | 'sharded', got {dp_update!r}"
            )
        if bucket_mb <= 0:
            raise ValueError(f"bucket_mb must be positive, got {bucket_mb}")
        self.dp_update = dp_update
        # Fused unscale+clip+Adam kernels for the sharded optimizer tail
        # (ops/kernels/fused_adam.py; docs/kernels.md).  None = auto:
        # on exactly when the sharded step runs plain Adam with no
        # weight decay — the one config whose optax op chain the fused
        # kernels replicate bit-for-bit (trajectory test-pinned).
        # Explicit True on an ineligible config is an error, not a
        # silent fallback.
        if fused_adam:
            if dp_update != "sharded":
                raise ValueError(
                    "fused_adam=True needs dp_update='sharded': the "
                    "fused kernels replace the sharded step's optimizer "
                    "tail (the fused step keeps optax's single jit)"
                )
            if self.optimizer_type != "adam":
                raise ValueError(
                    "fused_adam=True supports optimizer='adam' only "
                    f"(got {self.optimizer_type!r}): the kernels "
                    "replicate optax.adam's exact op chain"
                )
            if self.weight_decay:
                raise ValueError(
                    "fused_adam=True needs weight_decay=0: coupled L2 "
                    "prepends add_decayed_weights, which the fused "
                    "kernels do not replicate"
                )
        self.fused_adam = (
            dp_update == "sharded" and self.optimizer_type == "adam"
            and not self.weight_decay and lora is None
        ) if fused_adam is None else bool(fused_adam)
        self.bucket_mb = float(bucket_mb)
        if pipeline_schedule is not None:
            from ml_trainer_tpu.parallel.pipeline import SCHEDULES

            if pipeline_schedule not in SCHEDULES:
                raise ValueError(
                    f"pipeline_schedule must be one of {SCHEDULES}, got "
                    f"{pipeline_schedule!r}"
                )
        if isinstance(model, str):
            model = get_model(model, precision=self.precision)
        elif (
            self._compute_dtype is not None
            and hasattr(model, "dtype")
            and hasattr(model, "clone")
            and jnp.dtype(model.dtype) != jnp.dtype(self._compute_dtype)
        ):
            # Thread the compute dtype onto modules that carry a dtype
            # knob (the transformer zoo) so module-internal casts agree
            # with the trainer-level policy; params stay fp32
            # (flax's separate param_dtype).
            model = model.clone(dtype=self._compute_dtype)
        if pipeline_schedule is not None:
            if not (hasattr(model, "schedule") and hasattr(model, "clone")):
                raise ValueError(
                    "pipeline_schedule requires a pipelined model with a "
                    f"'schedule' knob (e.g. gpt2_pipe); got "
                    f"{type(model).__name__}"
                )
            if model.schedule != pipeline_schedule:
                model = model.clone(schedule=pipeline_schedule)
        self.pipeline_schedule = pipeline_schedule
        self.lora = None
        if lora is not None:
            from ml_trainer_tpu.lora import LoraConfig

            if isinstance(lora, dict):
                lora = LoraConfig(**lora)
            if not isinstance(lora, LoraConfig):
                raise ValueError(
                    f"lora must be a LoraConfig (or its kwargs dict), "
                    f"got {type(lora).__name__}"
                )
            if dp_update == "sharded":
                raise ValueError(
                    "lora training uses the fused update: the sharded "
                    "path's dim-0 partition rule does not cover the "
                    "masked optimizer state (dp_update='fused')"
                )
            if not (hasattr(model, "lora_rank") and hasattr(model, "clone")):
                raise ValueError(
                    "lora requires a model carrying the lora_* knobs "
                    f"(the GPT-2 family); got {type(model).__name__}"
                )
            # lora_slots stays 0: train mode — one trainable adapter as
            # ordinary params (serving pools are the engine's business).
            model = model.clone(
                lora_rank=int(lora.rank), lora_alpha=float(lora.alpha),
                lora_targets=tuple(lora.targets), lora_slots=0,
            )
            self.lora = lora
        self.model = model
        self._takes_train = _module_takes_train(model)
        self._takes_targets = _module_takes_targets(model)

        logger.info("Loading the model.")
        self._sharding_rules = sharding_rules
        if loader not in ("auto", "native", "python"):
            raise ValueError(
                f"loader must be 'auto' | 'native' | 'python', got {loader!r}"
            )
        self._loader_kind = loader
        if grad_accum_steps < 1:
            raise ValueError(f"grad_accum_steps must be >= 1, got {grad_accum_steps}")
        self.grad_accum_steps = int(grad_accum_steps)
        if steps_per_execution < 1:
            raise ValueError(
                f"steps_per_execution must be >= 1, got {steps_per_execution}"
            )
        self.steps_per_execution = int(steps_per_execution)
        self._shard_opt_state = bool(shard_opt_state)
        if grad_clip_norm is not None and grad_clip_norm <= 0:
            raise ValueError(
                f"grad_clip_norm must be positive, got {grad_clip_norm}"
            )
        self.grad_clip_norm = grad_clip_norm
        if ema_decay is not None and not (0.0 < ema_decay < 1.0):
            raise ValueError(
                f"ema_decay must be in (0, 1), got {ema_decay}"
            )
        self.ema_decay = ema_decay
        if moe_aux_weight < 0:
            raise ValueError(
                f"moe_aux_weight must be >= 0, got {moe_aux_weight}"
            )
        self.moe_aux_weight = float(moe_aux_weight)
        if early_stop_patience is not None and early_stop_patience < 1:
            raise ValueError(
                f"early_stop_patience must be >= 1, got {early_stop_patience}"
            )
        self.early_stop_patience = early_stop_patience
        self.save_best = bool(save_best)
        self.decay_exclude_bias_norm = bool(decay_exclude_bias_norm)
        # Per-host sharded full-state checkpoints (format v3): each process
        # writes exactly its addressable shards — no host-0 gather, no host
        # ever holds the full tree.  Requires the checkpoint dir to be
        # shared storage across hosts (GCS/NFS, the normal pod setup).
        # None = resolve from the state's shardings once they exist.
        self._sharded_ckpt = sharded_checkpoint
        self.nonfinite_guard = bool(nonfinite_guard)
        if rollback_bad_steps is not None and rollback_bad_steps < 1:
            raise ValueError(
                f"rollback_bad_steps must be >= 1, got {rollback_bad_steps}"
            )
        self.rollback_bad_steps = rollback_bad_steps
        if not (0.0 < rollback_lr_backoff <= 1.0):
            raise ValueError(
                f"rollback_lr_backoff must be in (0, 1], got "
                f"{rollback_lr_backoff}"
            )
        self.rollback_lr_backoff = float(rollback_lr_backoff)
        if save_every_steps is not None:
            if save_every_steps < 1:
                raise ValueError(
                    f"save_every_steps must be >= 1, got {save_every_steps}"
                )
            if self.steps_per_execution > 1:
                raise ValueError(
                    "save_every_steps (step-granular mid-epoch checkpoints) "
                    "requires steps_per_execution=1: the multi-step scan "
                    "dispatch has no per-batch cursor to checkpoint"
                )
        self.save_every_steps = save_every_steps
        self.handle_preemption = bool(handle_preemption)
        self.telemetry = bool(telemetry)
        if log_every_steps is not None:
            if log_every_steps < 1:
                raise ValueError(
                    f"log_every_steps must be >= 1, got {log_every_steps}"
                )
            self.log_every = int(log_every_steps)
        if desync_every_steps is not None and desync_every_steps < 1:
            raise ValueError(
                f"desync_every_steps must be >= 1, got {desync_every_steps}"
            )
        self.desync_every_steps = desync_every_steps
        if straggler_factor <= 1.0:
            raise ValueError(
                f"straggler_factor must be > 1, got {straggler_factor}"
            )
        self.straggler_factor = float(straggler_factor)
        from ml_trainer_tpu.telemetry.flight import get_recorder
        from ml_trainer_tpu.telemetry.spans import (
            PROFILE_ENV,
            PROFILE_TRIGGER_ENV,
            StepProfiler,
        )

        self._flight = get_recorder()
        self._telemetry: Optional[Any] = None  # built with the loaders
        self._cluster: Optional[Any] = None  # built with the telemetry
        self._memory_ledger: Optional[Any] = None  # built with the state
        self._profiler = StepProfiler("train")
        if self.telemetry:
            # Recompile forensics (telemetry/compile_watch.py): installed
            # BEFORE the first model-init compile so the ledger covers
            # every program this trainer builds.  Pure host bookkeeping —
            # the compiled programs and trajectory are untouched
            # (test-pinned).
            from ml_trainer_tpu.telemetry import compile_watch

            compile_watch.install()
            # A new trainer legitimately compiles (init, train/eval
            # steps): re-open warmup so a previous run's warm flag does
            # not mis-flag this construction as recompile incidents.
            compile_watch.mark_cold()
        # Per-step profiler polling only when something can trigger it.
        self._profile_hook = bool(
            self.telemetry
            or os.environ.get(PROFILE_ENV)
            or os.environ.get(PROFILE_TRIGGER_ENV)
        )
        self.preempted = False
        self._preempt_requested = False
        from ml_trainer_tpu.resilience.elastic import resolve_elastic

        self.elastic = resolve_elastic(elastic)
        if self.elastic is not None and self.steps_per_execution > 1:
            raise ValueError(
                "elastic reshape requires steps_per_execution=1: the "
                "drain needs the per-batch cursor the multi-step scan "
                "dispatch does not keep"
            )
        self.reshapes: list = []  # elastic mesh-reshape records this run
        self._reshape_request = None  # pending drain (set between steps)
        self._reshape_pending: Optional[dict] = None  # drained; reshape due
        self._live_hosts: list = (
            list(range(self.elastic.n_hosts)) if self.elastic else []
        )
        self.rollbacks = 0  # rollback-to-last-good events this run
        self.skipped_steps: list = []  # per-epoch skipped-step counts
        self._skipped_base = 0  # cumulative counter at current epoch start
        self._resume_mid: Optional[dict] = None  # mid-epoch resume cursor
        self._best_val = math.inf
        self._bad_epochs = 0
        if self.is_parallel:
            # Rendezvous — the init_process_group analog (ref: src/trainer.py:59).
            initialize_distributed(cfg.backend)
            self.mesh = create_mesh(mesh_shape)
        elif mesh_shape is not None:
            # An explicit mesh is honored without the multi-host rendezvous —
            # the normal single-process multi-chip TPU VM setup.
            self.mesh = create_mesh(mesh_shape)
        else:
            self.mesh = create_mesh(devices=jax.devices()[:1])
        # Batch divides over the data-like axes only; tensor/sequence axes
        # replicate the batch and shard the model instead.
        self._data_parallel = int(
            np.prod(
                [
                    self.mesh.shape[a]
                    for a in ("data", "fsdp")
                    if a in self.mesh.axis_names
                ]
            )
        ) if any(a in self.mesh.axis_names for a in ("data", "fsdp")) else 1
        self._batch_sharding = batch_sharding(self.mesh)
        self._replicated = replicated(self.mesh)
        if self.elastic is not None and process_count() == 1:
            # Simulated host groups: data is the outermost mesh axis, so
            # each host must own an equal contiguous block of data
            # replicas for the post-kill grid to stay a valid mesh.
            n_hosts = self.elastic.n_hosts
            data = int(self.mesh.shape.get("data", 1))
            if data < n_hosts or data % n_hosts or (
                int(self.mesh.size) % n_hosts
            ):
                raise ValueError(
                    f"elastic n_hosts={n_hosts} needs the mesh's data "
                    f"axis (size {data} over {int(self.mesh.size)} "
                    "devices) to split into equal host groups; pass a "
                    "mesh_shape whose data axis is divisible by n_hosts"
                )
        if self.dp_update == "sharded":
            # Pure-DP only: the sharded update re-expresses the gradient
            # psum as explicit reduce-scatter/all-gather over the data
            # axis; model-parallel axes would need their own collectives
            # composed in (tracked as future work in docs).
            model_axes = [
                a for a in self.mesh.axis_names
                if a != "data" and self.mesh.shape[a] > 1
            ]
            if self._sharding_rules is not None or model_axes:
                raise ValueError(
                    "dp_update='sharded' requires a pure data-parallel "
                    f"mesh with no sharding_rules; got mesh axes "
                    f"{dict(self.mesh.shape)}"
                )
            if self.steps_per_execution > 1:
                raise ValueError(
                    "dp_update='sharded' requires steps_per_execution=1"
                )
            if "data" not in self.mesh.axis_names or (
                self.mesh.shape["data"] < 2
            ):
                logger.warning(
                    "dp_update='sharded' on a single-replica mesh has "
                    "nothing to shard; falling back to the fused step."
                )
                self.dp_update = "fused"
                self.fused_adam = False
            elif not self._shard_opt_state:
                # The sharded update owns 1/N of the moments by
                # construction — ZeRO-1 placement is implied.
                logger.info(
                    "dp_update='sharded' implies shard_opt_state=True "
                    "(ZeRO-1 moment placement)."
                )
                self._shard_opt_state = True

        logger.info(f"Training on device: {jax.default_backend()}.")

        self.rng = jax.random.PRNGKey(cfg.seed)
        if label_smoothing and self._takes_targets:
            raise ValueError(
                "label_smoothing is not supported for models that "
                "compute their own loss (the chunked LM head applies "
                "plain cross entropy inside the forward)"
            )
        self.criterion = get_criterion(
            cfg.criterion, label_smoothing=label_smoothing
        )
        self.pred_function = get_prediction_function(cfg.pred_function)
        self.metric_fn = get_metric(cfg.metric, self.pred_function)
        # Epoch finalizer for nonlinear report metrics (e.g. perplexity
        # accumulates mean NLL and exponentiates ONCE per epoch — see
        # ops/metrics.py METRICS); identity for the linear ones.
        _fin = getattr(self.metric_fn, "finalize", None)
        self._metric_finalize = (
            (lambda v: float(_fin(v))) if _fin is not None else (lambda v: v)
        )
        if self._takes_targets and self.metric_fn is not None:
            raise ValueError(
                "metric must be None for models that compute their own "
                "loss (the forward returns a scalar, not logits to score); "
                f"got metric={cfg.metric!r}"
            )

        self.state: Optional[TrainState] = None
        self.train_loader: Optional[Loader] = None
        self.val_loader: Optional[Loader] = None
        self._plateau: Optional[PlateauController] = None
        self._lr_scale = 1.0
        self._eval_cache: dict = {}

        if datasets:
            train_set, val_set = datasets
            # Retained for elastic reshapes: the 'per_device' batch
            # policy rebuilds the loaders at the shrunk global batch.
            self._datasets = (train_set, val_set)
            self._build_loaders(train_set, val_set, batch_size, cfg)
            self._build_state_and_steps(cfg)
        else:
            logger.warning("Testing only available. No datasets in arguments.")

    # ------------------------------------------------------------------ data
    def _build_loaders(self, train_set, val_set, batch_size, cfg) -> None:
        logger.info("Loading training and validation set.")
        logger.info("Preparing the data.")
        d = self._data_parallel * self.grad_accum_steps
        # Reference semantics: global batch ÷ world, floored at 1
        # (ref: src/trainer.py:63-64).  Here the division happens through the
        # mesh sharding, so we only round the global batch down to a multiple
        # of the data-parallel degree × grad-accum microbatch count (and up
        # to at least one sample per chip per microbatch).
        eff = max(batch_size // d, 1) * d
        if eff != batch_size:
            logger.warning(
                f"Global batch {batch_size} adjusted to {eff} to divide "
                f"across {d} data-parallel devices."
            )
        drop_last = d > 1  # static shapes across the mesh
        train_sampler = None
        if self.is_parallel:
            train_sampler = ShardedSampler(
                len(train_set) if hasattr(train_set, "__len__") else 0,
                num_replicas=process_count(),
                rank=process_index(),
                shuffle=True,
                seed=cfg.seed,
            )
        per_host = eff // process_count()
        self.global_batch = eff

        def build(dataset, shuffle, sampler, seed):
            plan = None
            if self._loader_kind in ("auto", "native"):
                from ml_trainer_tpu.data.native import (
                    native_available,
                    native_plan,
                )

                plan = native_plan(dataset)
                if plan is not None and not native_available():
                    plan = None
                if plan is not None and self._loader_kind == "auto":
                    # The native loader pads a ragged final batch by
                    # wrapping (repeats leading samples); the Python Loader
                    # yields a short batch.  'auto' must never change batch
                    # semantics, so fall back unless the split is exact.
                    n = len(sampler) if sampler is not None else len(dataset)
                    if not drop_last and n % per_host != 0:
                        plan = None
                if self._loader_kind == "native" and plan is None:
                    raise ValueError(
                        "loader='native' requires a uint8 NHWC ArrayDataset "
                        "with the reference augmentation pipeline (and a "
                        "working g++); got an unsupported dataset/transform"
                    )
            if plan is not None:
                from ml_trainer_tpu.data.native import NativeLoader

                logger.info("Using the native (C++) input pipeline.")
                return NativeLoader(
                    dataset, batch_size=per_host, shuffle=shuffle,
                    sampler=sampler, drop_last=drop_last, seed=seed, **plan,
                )
            return Loader(
                dataset, batch_size=per_host, shuffle=shuffle,
                sampler=sampler, drop_last=drop_last, seed=seed,
            )

        self.train_loader = build(
            train_set, train_sampler is None, train_sampler, cfg.seed
        )
        # The reference evaluates the FULL validation set on every rank with
        # shuffle=True (ref: src/trainer.py:79) — kept, modulo drop_last for
        # static shapes on a sharded mesh (documented divergence).
        self.val_loader = build(val_set, True, None, cfg.seed + 1)
        if len(self.train_loader) == 0 or len(self.val_loader) == 0:
            raise ValueError(
                f"Loader yields no batches (train {len(self.train_loader)}, "
                f"val {len(self.val_loader)}): dataset shard smaller than the "
                f"per-host batch {per_host} with drop_last={drop_last}. "
                "Reduce the global batch size or grow the dataset."
            )
        logger.debug(
            "Processes {}/{} ({:.0f}%) of train data".format(
                len(self.train_loader.sampler),
                len(self.train_loader.dataset),
                100.0
                * len(self.train_loader.sampler)
                / len(self.train_loader.dataset),
            )
        )
        logger.debug(
            "Processes {}/{} ({:.0f}%) of validation data".format(
                len(self.val_loader.sampler),
                len(self.val_loader.dataset),
                100.0
                * len(self.val_loader.sampler)
                / len(self.val_loader.dataset),
            )
        )

    # ----------------------------------------------------------------- state
    def _apply(self, variables, x, train: bool, rngs=None, mutable=False,
               targets=None):
        kwargs = {}
        if self._takes_train:
            kwargs["train"] = train
        if targets is not None:
            kwargs["targets"] = targets
        if mutable:
            if not isinstance(mutable, (list, tuple)):
                raise TypeError(
                    f"mutable must be False or a list of collection names, "
                    f"got {mutable!r}"
                )
            return self.model.apply(
                variables, x, rngs=rngs, mutable=list(mutable), **kwargs
            )
        return self.model.apply(variables, x, rngs=rngs, **kwargs)

    def _build_state_and_steps(self, cfg) -> None:
        sample_x, _ = next(iter(self.train_loader))
        sample_x = jnp.asarray(sample_x[: max(self.global_batch // process_count(), 1)])
        self.rng, init_rng, dropout_rng = jax.random.split(self.rng, 3)
        init_kwargs = {"train": False} if self._takes_train else {}
        # jit the init: flax executes it eagerly by default (one device
        # dispatch per op), which over a remote TPU tunnel is one round
        # trip per op — minutes for a ResNet.  Jitted it is one compile +
        # one execution.
        init_fn = jax.jit(
            self.model.init,
            static_argnames="train" if self._takes_train else (),
        )
        variables = init_fn(
            {"params": init_rng, "dropout": dropout_rng}, sample_x, **init_kwargs
        )
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        self._has_batch_stats = bool(batch_stats)
        if self.dp_update == "sharded" and self._has_batch_stats:
            raise ValueError(
                "dp_update='sharded' does not support batch_stats models "
                "(per-replica BatchNorm statistics inside the shard_map "
                "body would diverge from the fused global-batch stats); "
                "use the fused step for BatchNorm models"
            )
        # Detect sown auxiliary losses (MoEMLP's load-balance term) with a
        # shape-only trace of the TRAIN-mode forward — init() runs at
        # train=False, which would miss losses gated on training (router
        # z-loss variants).  batch_stats must stay mutable during the probe
        # or BatchNorm models would fail the trace.  The train step then
        # captures and applies whatever the probe finds.
        probe_cols = ["losses"] + (["batch_stats"] if batch_stats else [])
        mut_shapes = jax.eval_shape(
            lambda v, r: self._apply(
                v, sample_x, train=True, rngs={"dropout": r},
                mutable=probe_cols,
            )[1],
            variables, dropout_rng,
        )
        self._has_aux_losses = bool(mut_shapes.get("losses"))

        self.steps_per_epoch = len(self.train_loader)
        self.lr_schedule = make_lr_schedule(
            cfg.scheduler, cfg.lr, self.steps_per_epoch,
            # epochs may be None (eval-only Trainer): the warmup schedules
            # then fall back to their documented fixed horizon.
            total_steps=(
                self.steps_per_epoch * self.epochs if self.epochs else None
            ),
        )
        self.tx = get_optimizer(
            cfg.optimizer, self.lr_schedule, cfg.momentum, cfg.weight_decay,
            decay_mask=(
                decay_mask_matrices_only
                if self.decay_exclude_bias_norm else None
            ),
        )
        # Always chain (both clip and identity carry EmptyState), so the
        # opt_state pytree structure — and therefore checkpoints — do not
        # depend on whether clipping is on: the flag can toggle across a
        # resume.  The sharded-update path keeps the identity slot and
        # clips manually instead: inside its step the optimizer sees 1/N
        # shards, so optax's clip would compute a per-replica norm — the
        # step psums the true global norm itself (same math, same
        # opt_state structure).
        self.tx = optax.chain(
            optax.clip_by_global_norm(self.grad_clip_norm)
            if (self.grad_clip_norm is not None
                and self.dp_update != "sharded")
            else optax.identity(),
            self.tx,
        )
        if self.lora is not None:
            # Freeze the base: only *_lora_A/*_lora_B leaves reach the
            # optimizer (clip included — the global norm is the
            # ADAPTER grads' norm); frozen leaves get set_to_zero
            # updates and, through optax's masking, NO optimizer state
            # — so moments shrink to the adapter fraction, which the
            # memory ledger's opt_state component makes visible.
            from ml_trainer_tpu.lora import lora_param_labels

            labels = lora_param_labels(params)
            n_lora = sum(
                1 for v in jax.tree.leaves(labels) if v == "lora"
            )
            if not n_lora:
                raise ValueError(
                    "Trainer(lora=...) found no *_lora_A/*_lora_B "
                    "params — do the configured targets exist on this "
                    "model?"
                )
            self.tx = optax.multi_transform(
                {"lora": self.tx, "frozen": optax.set_to_zero()},
                labels,
            )
            logger.info(
                f"LoRA: training {n_lora} adapter leaves (rank "
                f"{self.lora.rank}, targets {self.lora.targets}); "
                f"{len(jax.tree.leaves(labels)) - n_lora} base leaves "
                "frozen with no optimizer state."
            )
        if cfg.scheduler == "ReduceLROnPlateau":
            self._plateau = PlateauController(cfg.lr)

        self.rng, state_rng = jax.random.split(self.rng)
        # Place params per the sharding rules (replicated when rules=None —
        # the DDP initial-broadcast analog, ref: src/trainer.py:98).
        # Optimizer state is created FROM the placed params, so momenta etc.
        # inherit each param's sharding; leaves tx.init creates from scratch
        # (step counters) land on the default device and are re-placed
        # replicated so the whole state lives on the mesh.
        from ml_trainer_tpu.parallel import shard_params
        from ml_trainer_tpu.parallel.tp_rules import validate_tp_mesh

        if self._sharding_rules is not None:
            # Fail fast on head-splitting tensor degrees (GQA: tensor
            # must divide num_kv_heads) before any placement happens.
            validate_tp_mesh(self.model, self.mesh)
        params = shard_params(params, self.mesh, self._sharding_rules)
        if batch_stats:
            batch_stats = shard_params(
                batch_stats, self.mesh, self._sharding_rules
            )
        if self._shard_opt_state and self._sharding_rules is None:
            # Pure-DP ZeRO-1: decide shardings from SHAPES and jit-init with
            # out_shardings so the moments are BORN partitioned — the full
            # replicated tree never materializes (tx.init would otherwise be
            # the peak-memory moment on exactly the memory-bound runs this
            # flag exists for).
            from ml_trainer_tpu.parallel import zero1_opt_shardings

            out_sh = zero1_opt_shardings(
                jax.eval_shape(self.tx.init, params), self.mesh
            )
            opt_state = jax.jit(self.tx.init, out_shardings=out_sh)(params)
        else:
            if self._sharding_rules is not None:
                # Rule-sharded params (TP/FSDP): moments must INHERIT each
                # param's sharding (replicating them is the memory blowup
                # sharding exists to prevent).  jit alone erases the
                # shardings (zeros_like has no data dependence for GSPMD to
                # propagate) and eager init would crash on multi-host
                # non-addressable arrays — so jit with explicit
                # out_shardings, mapped from the params by shape (shapes
                # repeating across layers carry the same rule; ambiguous
                # shapes fall back replicated, a memory — not correctness —
                # concession).
                by_shape: dict = {}
                for p in jax.tree.leaves(params):
                    cur = by_shape.get(p.shape)
                    if cur is None:
                        by_shape[p.shape] = p.sharding
                    elif cur != p.sharding:
                        by_shape[p.shape] = self._replicated
                out_sh = jax.tree.map(
                    lambda l: by_shape.get(l.shape, self._replicated),
                    jax.eval_shape(self.tx.init, params),
                )
                opt_state = jax.jit(self.tx.init, out_shardings=out_sh)(params)
            else:
                # Replicated params (pure DP, incl. the single-chip tunnel
                # where eager per-op dispatch is the hazard): jit is safe,
                # the placement re-places everything replicated anyway.
                # place_tree, not per-leaf device_put: multi-host the leaf
                # storm is both O(leaves) DCN broadcasts and a gloo-CPU
                # abort (parallel/sharding.py).
                from ml_trainer_tpu.parallel import place_tree

                opt_raw = jax.jit(self.tx.init)(params)
                opt_state = place_tree(
                    opt_raw,
                    jax.tree.map(
                        lambda x: x.sharding
                        if isinstance(
                            getattr(x, "sharding", None),
                            jax.sharding.NamedSharding,
                        )
                        else self._replicated,
                        opt_raw,
                    ),
                )
            if self._shard_opt_state:
                # Model-sharded params (TP/FSDP rules): re-place only the
                # still-replicated leaves, leaving rule-sharded moments be.
                from ml_trainer_tpu.parallel import shard_opt_state as _shard_opt

                opt_state = _shard_opt(opt_state, self.mesh)
        # EMA weights start as a copy of the placed params (same shardings).
        ema_params = (
            jax.tree.map(jnp.copy, params) if self.ema_decay is not None
            else None
        )
        # The replicated host-side scalars (step/rng/guard counters) place
        # in ONE program — see place_tree for why per-leaf device_put is
        # not multi-host-safe.
        from ml_trainer_tpu.parallel import place_tree

        host_scalars = {
            "step": jnp.zeros((), jnp.int32),
            "rng": state_rng,
            "skipped": jnp.zeros((), jnp.int32),
            "streak": jnp.zeros((), jnp.int32),
        }
        if self._loss_scale_cfg is not None:
            # Dynamic loss scaling: the scale and its growth counter are
            # on-device state, updated by the same compiled step that
            # uses them (precision.py semantics).
            host_scalars["loss_scale"] = jnp.asarray(
                self._loss_scale_cfg.init_scale, jnp.float32
            )
            host_scalars["good"] = jnp.zeros((), jnp.int32)
        scalars = place_tree(
            host_scalars,
            {k: self._replicated for k in host_scalars},
        )
        self.state = TrainState(
            step=scalars["step"],
            params=params,
            opt_state=opt_state,
            batch_stats=batch_stats,
            rng=scalars["rng"],
            ema_params=ema_params,
            # Guard counters ride in the state so the compiled step can
            # maintain them without a host sync (fetched once per epoch).
            skipped_steps=scalars["skipped"],
            bad_streak=scalars["streak"],
            loss_scale=scalars.get("loss_scale"),
            good_steps=scalars.get("good"),
        )
        self._state_shardings = jax.tree.map(lambda x: x.sharding, self.state)
        if self._sharded_ckpt is None:
            # Auto: the host-0 v2 gather is a deadlock (not merely a RAM
            # spike) exactly when some leaf is partitioned across
            # processes — one process would launch a global allgather the
            # others never join.  Replicated-only multi-host state keeps
            # the reference's rank-0 format for compatibility.
            self._sharded_ckpt = process_count() > 1 and any(
                not leaf.is_fully_addressable
                and not getattr(leaf, "is_fully_replicated", False)
                for leaf in jax.tree.leaves(self.state)
            )
            if self._sharded_ckpt:
                logger.info(
                    "Partitioned multi-host state: using per-host sharded "
                    "checkpoints (sharded_checkpoint=True)."
                )
        self._bucket_plan = None
        if self.dp_update == "sharded":
            from ml_trainer_tpu.parallel import plan_grad_buckets

            self._bucket_plan = plan_grad_buckets(
                params, int(self.mesh.shape["data"]),
                bucket_bytes=int(self.bucket_mb * 2 ** 20),
            )
            logger.info(
                f"Sharded DP update: {len(self._bucket_plan.buckets)} "
                f"reduce-scatter buckets over data={self.mesh.shape['data']} "
                f"(bucket_mb={self.bucket_mb}, analytic overlap fraction "
                f"{self._bucket_plan.overlap_fraction:.2f})."
            )
        # Batch geometry for the telemetry spine AND the memory ledger
        # (set regardless of the telemetry flag so an on-demand
        # memory.train_ledger(trainer) can always price the batch).
        self._batch_geometry = (self.global_batch,) + tuple(sample_x.shape[1:])
        self._batch_dtype = sample_x.dtype
        if self.telemetry:
            from ml_trainer_tpu.telemetry.cluster import ClusterTelemetry
            from ml_trainer_tpu.telemetry.train_metrics import TrainTelemetry

            # Cluster aggregation rides the telemetry flag: host-local
            # heartbeats at every sync, ONE small allgather per epoch
            # (degenerate single-host publish when not distributed).
            self._cluster = ClusterTelemetry(
                flight=self._flight,
                straggler_factor=self.straggler_factor,
                # Straggler VERDICT hook: the elastic controller turns a
                # straggler past its reshape factor into a drain+reshape
                # request (self-gating — a no-op without elastic=).
                on_straggler=self._on_straggler_verdict,
            )
            self._telemetry = TrainTelemetry(
                model=self.model,
                model_name=type(self.model).__name__,
                global_batch=self.global_batch,
                batch_shape=self._batch_geometry,
                flight=self._flight,
                cluster=self._cluster,
                compute_dtype=self.precision.label(),
                overlap_fraction=(
                    self._bucket_plan.overlap_fraction
                    if self._bucket_plan is not None else None
                ),
            )
            # HBM ledger (telemetry/memory.py): a metadata-only walk of
            # the state just placed — published once here (the analytic
            # components never change during the run) and attached to
            # every flight dump, with the live per-device view, so OOM
            # forensics name the resident components.
            from ml_trainer_tpu.telemetry import (
                compile_watch,
                memory as _memory,
            )

            self._memory_ledger = _memory.train_ledger(self)
            self._memory_ledger.publish()
            self._flight.record(
                "memory_ledger",
                resident_bytes=int(self._memory_ledger.resident_bytes()),
                peak_bytes=int(self._memory_ledger.peak_bytes()),
                components={
                    c.name: int(c.bytes)
                    for c in self._memory_ledger.components
                },
            )
            self._flight.register_context_provider(
                "memory", _memory.memory_snapshot_payload
            )
            self._flight.register_context_provider(
                "compile_events",
                lambda: compile_watch.recent_events_payload(16),
            )
            # The committed graft-lint baseline's fingerprint rides every
            # dump: post-mortems know which static-contract set this
            # build was checked against (analysis/__init__.py).
            from ml_trainer_tpu.analysis import register_flight_context

            register_flight_context(self._flight)
            logger.info(
                "memory_ledger",
                resident_mb=round(
                    self._memory_ledger.resident_bytes() / 2 ** 20, 2
                ),
                peak_mb=round(
                    self._memory_ledger.peak_bytes() / 2 ** 20, 2
                ),
            )
        self._build_steps()

    def _build_steps(self) -> None:
        """(Re)build the compiled train/eval steps against the CURRENT
        mesh, shardings and bucket plan.  Split from
        ``_build_state_and_steps`` so an elastic reshape
        (``_perform_reshape``) can rebuild the programs after swapping
        the mesh under the same Trainer."""
        train_step = (
            self._make_sharded_train_step()
            if self.dp_update == "sharded" else self._make_train_step()
        )
        # Pin the output state to the SAME shardings it was born with: the
        # state's placement is a class invariant (resume/device_put, the
        # export path, and the v3 checkpoint writer all key off
        # _state_shardings).  Left unpinned, GSPMD may return some params
        # leaves data-PARTITIONED under ZeRO-1 (the sharded moments
        # propagate into the update), which silently turns the
        # weights-export into a cross-host collective — observed as a
        # deadlock against the v3 commit barrier.  Pinning restores ZeRO-1
        # semantics proper: the weight allgather happens INSIDE the
        # compiled step.
        step_out_shardings = (
            (self._state_shardings, None, None, None)
            if self.telemetry else (self._state_shardings, None, None)
        )
        self._train_step = jax.jit(
            train_step, donate_argnums=0, out_shardings=step_out_shardings
        )
        if self.steps_per_execution > 1:
            # K optimizer steps per dispatch: scan the SAME step function
            # over stacked batches [K, B, ...] — identical update sequence,
            # one host round-trip per K steps.
            def multi_step(state, xs, ys, lr_scale):
                def body(state, xy):
                    out = train_step(state, *xy, lr_scale)
                    return out[0], out[1:]

                state, outs = jax.lax.scan(body, state, (xs, ys))
                losses, metrics = outs[0], outs[1]
                if self.telemetry:
                    # The dispatch's LAST step's stats — what the host
                    # would have seen stepping per-batch at this cadence.
                    last_stats = jax.tree.map(lambda s: s[-1], outs[2])
                    return state, losses.sum(), metrics.sum(), last_stats
                return state, losses.sum(), metrics.sum()

            self._train_multi_step = jax.jit(
                multi_step, donate_argnums=0,
                out_shardings=step_out_shardings,
            )
            # Stacked batches put the step dim first: same data-axis split
            # on dim 1 (and sequence on dim 2 when live).
            spec = self._batch_sharding.spec
            self._stacked_sharding = jax.sharding.NamedSharding(
                self.mesh, P(None, *spec)
            )
        self._eval_step, self._eval_multi_step = self._make_eval_step(
            self.model, self._takes_train, self._has_batch_stats,
            multi=self.steps_per_execution > 1,
        )

    def _make_grads_for(self):
        """The shared forward/backward closure of both train-step flavors:
        ``grads_for(params, batch_stats, x, y, dropout_rng, scale=None)``
        returns ``(grads, new_bs, loss, metric_val)`` where ``grads``
        differentiate ``scale * loss`` (the caller unscales once, after
        any accumulation) and ``loss``/``metric_val`` are unscaled.  With
        an active bf16 policy, master params and float inputs cast to the
        compute dtype at the top (gradients come home fp32 through the
        cast's vjp) and outputs cast back to fp32 before the criterion;
        at fp32 the traced program is exactly the pre-policy one."""
        criterion, metric_fn = self.criterion, self.metric_fn
        has_bs, model_apply = self._has_batch_stats, self._apply
        takes_targets = self._takes_targets
        has_aux = getattr(self, "_has_aux_losses", False)
        aux_weight = self.moe_aux_weight
        compute_dtype = self._compute_dtype
        if compute_dtype is not None:
            from ml_trainer_tpu.precision import cast_floating, cast_like

        def grads_for(params, batch_stats, x, y, dropout_rng, scale=None):
            def loss_fn(params):
                if compute_dtype is not None:
                    p_apply = cast_floating(params, compute_dtype)
                    x_apply = (
                        x.astype(compute_dtype)
                        if jnp.issubdtype(x.dtype, jnp.inexact) else x
                    )
                else:
                    p_apply, x_apply = params, x
                variables = {"params": p_apply}
                if has_bs:
                    variables["batch_stats"] = batch_stats
                mutable_cols = (["batch_stats"] if has_bs else []) + (
                    ["losses"] if has_aux else []
                )
                # Self-loss models (GPT2 chunked LM head): labels go
                # through the forward, the output IS the loss.
                fwd_targets = y if takes_targets else None
                if mutable_cols:
                    out, mutated = model_apply(
                        variables, x_apply, train=True,
                        rngs={"dropout": dropout_rng}, mutable=mutable_cols,
                        targets=fwd_targets,
                    )
                    new_bs = mutated.get("batch_stats", batch_stats)
                    if compute_dtype is not None and has_bs:
                        # Stats mutated under bf16 come home at the state
                        # dtype (checkpoints and where-selects depend on
                        # dtype-stable state leaves).
                        new_bs = cast_like(new_bs, batch_stats)
                else:
                    out = model_apply(
                        variables, x_apply, train=True,
                        rngs={"dropout": dropout_rng}, targets=fwd_targets,
                    )
                    mutated = {}
                    new_bs = batch_stats
                if compute_dtype is not None and hasattr(out, "astype"):
                    # Precision.output: criterion/metrics read fp32.
                    out = out.astype(jnp.float32)
                loss = out if takes_targets else criterion(out, y)
                if has_aux:
                    # Sown auxiliary losses (e.g. MoE load-balance,
                    # models/moe.py): summed over layers, scaled once.
                    aux_terms = jax.tree.leaves(mutated.get("losses", {}))
                    if aux_terms:
                        loss = loss + aux_weight * sum(aux_terms)
                scaled = loss if scale is None else loss * scale
                return scaled, (loss, out, new_bs)

            (_, (loss, out, new_bs)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            metric_val = (
                metric_fn(out, y) if metric_fn is not None else jnp.zeros(())
            )
            return grads, new_bs, loss, metric_val

        return grads_for

    def _scale_streak_updates(self, state, ok, cfg, one, zero):
        """Shared guard bookkeeping for loss scaling: the bad-streak rule
        (an overflow is the scale's fault while it can still back off —
        it must NOT advance the rollback streak) and the dynamic
        scale/growth-counter arithmetic.  Returns
        ``(new_streak, replace_kwargs)``."""
        if cfg is None:
            return jnp.where(ok, zero, state.bad_streak + one), {}
        attributed = state.loss_scale > cfg.min_scale
        new_streak = jnp.where(
            ok, zero,
            jnp.where(attributed, state.bad_streak, state.bad_streak + one),
        )
        grown = state.good_steps + one >= cfg.growth_interval
        new_scale = jnp.where(
            ok,
            jnp.where(
                grown,
                jnp.minimum(
                    state.loss_scale * cfg.growth_factor, cfg.max_scale
                ),
                state.loss_scale,
            ),
            jnp.maximum(state.loss_scale * cfg.backoff_factor, cfg.min_scale),
        )
        new_good = jnp.where(
            ok & ~grown, state.good_steps + one, jnp.zeros_like(
                state.good_steps
            )
        )
        return new_streak, {"loss_scale": new_scale, "good_steps": new_good}

    def _make_train_step(self):
        tx = self.tx
        accum = self.grad_accum_steps
        ema_decay = self.ema_decay
        guard = self.nonfinite_guard
        telemetry = self.telemetry
        cfg = self._loss_scale_cfg
        grads_for = self._make_grads_for()

        def train_step(state: TrainState, x, y, lr_scale):
            rng, dropout_rng = jax.random.split(state.rng)
            scale = state.loss_scale if cfg is not None else None
            # Data-parallel gradient averaging happens implicitly in
            # grads_for: the batch is sharded over the mesh's data axis while
            # params are replicated, so XLA inserts the psum the reference
            # performs via DDP's bucketed all-reduce
            # (ref: src/trainer.py:98, 152-158).
            if accum == 1:
                grads, new_bs, loss, metric_val = grads_for(
                    state.params, state.batch_stats, x, y, dropout_rng, scale
                )
                if scale is not None:
                    grads = jax.tree.map(lambda g: g / scale, grads)
            else:
                # lax.scan over microbatches: gradients sum on-device, one
                # optimizer update per global batch (GPT-2 grad-accum
                # config, BASELINE.json configs[4]).
                micro = x.shape[0] // accum
                xm = x.reshape((accum, micro) + x.shape[1:])
                ym = y.reshape((accum, micro) + y.shape[1:])

                def body(carry, xy):
                    bs, g_sum, l_sum, m_sum, drng = carry
                    drng, sub = jax.random.split(drng)
                    g, bs, l, m = grads_for(state.params, bs, *xy, sub, scale)
                    g_sum = jax.tree.map(jnp.add, g_sum, g)
                    return (bs, g_sum, l_sum + l, m_sum + m, drng), None

                zeros = jax.tree.map(jnp.zeros_like, state.params)
                (new_bs, g_sum, l_sum, m_sum, _), _ = jax.lax.scan(
                    body,
                    (state.batch_stats, zeros, jnp.zeros(()), jnp.zeros(()),
                     dropout_rng),
                    (xm, ym),
                )
                if scale is None:
                    grads = jax.tree.map(lambda g: g / accum, g_sum)
                else:
                    # One unscale folds the microbatch mean and the loss
                    # scale (the scale was constant across the scan).
                    grads = jax.tree.map(lambda g: g / (accum * scale), g_sum)
                loss = l_sum / accum
                metric_val = m_sum / accum
            updates, new_opt = tx.update(grads, state.opt_state, state.params)
            updates = jax.tree.map(lambda u: u * lr_scale, updates)
            new_params = optax.apply_updates(state.params, updates)
            new_ema = (
                jax.tree.map(
                    lambda e, p: ema_decay * e + (1.0 - ema_decay) * p,
                    state.ema_params, new_params,
                )
                if ema_decay is not None else state.ema_params
            )
            new_skipped, new_streak = state.skipped_steps, state.bad_streak
            replace_kwargs = {}
            raw_loss = loss  # pre-guard: telemetry must SEE the NaN
            if guard:
                # On-device all-finite guard: a non-finite loss or any
                # non-finite gradient leaf reverts every learned quantity
                # to the pre-step value via `where` selects — same
                # compiled program either way (no lax.cond branch, no
                # recompile, no host sync).  step/rng still advance (the
                # batch was consumed; the LR schedule and dropout stream
                # stay aligned with the data), while the optimizer's
                # inner counters revert with the moments — the skipped
                # step never happened as far as Adam bias correction is
                # concerned.  When everything is finite, `where(ok, n, o)
                # == n` exactly, so guarded and unguarded trajectories
                # are bit-identical.
                ok = jnp.isfinite(loss)
                for g in jax.tree.leaves(grads):
                    ok = ok & jnp.all(jnp.isfinite(g))

                def sel(n, o):
                    return jax.tree.map(
                        lambda a, b: jnp.where(ok, a, b), n, o
                    )

                new_params = sel(new_params, state.params)
                new_opt = sel(new_opt, state.opt_state)
                new_bs = sel(new_bs, state.batch_stats)
                if ema_decay is not None:
                    new_ema = sel(new_ema, state.ema_params)
                one = jnp.ones((), jnp.int32)
                zero = jnp.zeros((), jnp.int32)
                new_skipped = state.skipped_steps + jnp.where(ok, zero, one)
                # Loss scaling folds into the guard here: an overflow
                # halves the scale WITHOUT advancing the rollback streak
                # (fp32 / no-scaling keeps the exact pre-policy streak).
                new_streak, replace_kwargs = self._scale_streak_updates(
                    state, ok, cfg, one, zero
                )
                # A skipped step contributes zero to the epoch sums so
                # one NaN cannot poison the whole epoch's history.
                loss = jnp.where(ok, loss, jnp.zeros_like(loss))
                metric_val = jnp.where(
                    ok, metric_val, jnp.zeros_like(metric_val)
                )
            new_state = state.replace(
                step=state.step + 1,
                params=new_params,
                opt_state=new_opt,
                batch_stats=new_bs,
                rng=rng,
                ema_params=new_ema,
                skipped_steps=new_skipped,
                bad_streak=new_streak,
                **replace_kwargs,
            )
            if telemetry:
                # On-device step stats (telemetry/train_metrics.py):
                # pure functions of values this program already holds —
                # same trajectory, same single compiled program, no
                # host sync; the host fetches them at the log cadence.
                from ml_trainer_tpu.telemetry.train_metrics import (
                    step_stats,
                )

                stats = step_stats(raw_loss, grads, updates, new_params)
                return new_state, loss, metric_val, stats
            return new_state, loss, metric_val

        return train_step

    def _make_sharded_train_step(self):
        """The bucketed reduce-scatter + cross-replica sharded-update step
        (dp_update='sharded'; arXiv 2004.13336 composed with TorchTitan's
        bucketed comm/compute overlap).

        One ``shard_map`` over the pure-DP data axis replaces the
        compiler-inserted tail psum with explicit structure:

        1. each replica runs forward/backward on its batch shard (local
           gradients, never globally reduced in full);
        2. gradients leave through per-bucket ``reduce_scatter`` calls in
           reverse topological order — each bucket's collective depends
           only on its own leaves' gradients, so the XLA latency-hiding
           scheduler can run it while earlier layers' gradients are
           still computing (a single fused psum serializes after the
           whole backward);
        3. the optimizer update runs on this replica's 1/N shard of
           grads/params/ZeRO-1 moments (update FLOPs and moment memory
           ÷ N); grad clipping psums the true global norm first;
        4. fresh weights return via per-bucket ``all_gather``.

        Math matches the fused step (trajectory-equality test-pinned):
        reduce-scatter of local-mean grads / N == the global-mean psum,
        and every optimizer in the zoo is elementwise per leaf."""
        from jax import lax

        from ml_trainer_tpu.parallel import (
            bucketed_all_gather,
            bucketed_reduce_scatter,
            collectives as col,
        )
        from ml_trainer_tpu.parallel.compat import shard_map
        from ml_trainer_tpu.telemetry.train_metrics import _global_norm

        mesh = self.mesh
        n = int(mesh.shape["data"])
        plan = self._bucket_plan
        tx = self.tx
        accum = self.grad_accum_steps
        ema_decay = self.ema_decay
        guard = self.nonfinite_guard
        telemetry = self.telemetry
        cfg = self._loss_scale_cfg
        clip = self.grad_clip_norm
        grads_for = self._make_grads_for()
        param_leaves = jax.tree.leaves(self.state.params)
        full_shapes = [leaf.shape for leaf in param_leaves]
        # Fused optimizer-tail kernels (ops/kernels/fused_adam.py):
        # eligibility was resolved in __init__ (plain Adam, wd=0).  The
        # fused path computes bit-for-bit the unfused optax chain —
        # pinned by the golden-trajectory test — while reading each
        # shard once per pass instead of once per optax op.
        use_fused = self.fused_adam
        lr_sched = self.lr_schedule
        if use_fused:
            from ml_trainer_tpu.ops.kernels.fused_adam import (
                adam_scalars,
                fused_adam_update,
                unscale_sqsum,
            )

        def split_sq(leaves):
            """(local-shard sq-sum, replicated sq-sum) of a mixed tree —
            the psum of the first plus the second is the global sq-norm."""
            loc = jnp.zeros((), jnp.float32)
            rep = jnp.zeros((), jnp.float32)
            for i, leaf in enumerate(leaves):
                s = jnp.sum(jnp.square(leaf.astype(jnp.float32)))
                loc, rep = (loc + s, rep) if plan.sharded[i] else (loc, rep + s)
            return loc, rep

        def body(state: TrainState, x, y, lr_scale):
            rng, dropout_rng = jax.random.split(state.rng)
            scale = state.loss_scale if cfg is not None else None
            if accum == 1:
                grads, _, loss, metric_val = grads_for(
                    state.params, state.batch_stats, x, y, dropout_rng, scale
                )
            else:
                micro = x.shape[0] // accum
                xm = x.reshape((accum, micro) + x.shape[1:])
                ym = y.reshape((accum, micro) + y.shape[1:])

                def accum_body(carry, xy):
                    bs, g_sum, l_sum, m_sum, drng = carry
                    drng, sub = jax.random.split(drng)
                    g, bs, l, m = grads_for(state.params, bs, *xy, sub, scale)
                    g_sum = jax.tree.map(jnp.add, g_sum, g)
                    return (bs, g_sum, l_sum + l, m_sum + m, drng), None

                zeros = jax.tree.map(jnp.zeros_like, state.params)
                (_, grads, l_sum, m_sum, _), _ = jax.lax.scan(
                    accum_body,
                    (state.batch_stats, zeros, jnp.zeros(()), jnp.zeros(()),
                     dropout_rng),
                    (xm, ym),
                )
                loss = l_sum / accum
                metric_val = m_sum / accum
            # Epoch accounting reads global means (what the fused step's
            # sharded-batch criterion computes implicitly).
            loss = col.pmean(loss, "data")
            metric_val = col.pmean(metric_val, "data")

            g_leaves, g_def = jax.tree.flatten(grads)
            # (2) bucketed reduce-scatter: one collective per bucket, in
            # reverse backward-production order; each replica keeps its
            # 1/N dim-0 shard, summed across replicas.
            g_leaves = bucketed_reduce_scatter(g_leaves, plan, "data")
            rep_idx = [
                i for i in range(len(g_leaves)) if not plan.sharded[i]
            ]
            if rep_idx:
                # Indivisible leaves (rare: odd-dim heads, scalars) keep a
                # replicated update — ONE fused psum over their concat.
                flat = col.psum(
                    jnp.concatenate(
                        [g_leaves[i].reshape(-1) for i in rep_idx]
                    ),
                    "data",
                )
                off = 0
                for i in rep_idx:
                    size = int(np.prod(g_leaves[i].shape, initial=1))
                    g_leaves[i] = flat[off:off + size].reshape(
                        g_leaves[i].shape
                    )
                    off += size
            # Scatter/psum SUMMED local-mean grads: /n folds the replica
            # mean, /accum the microbatch mean, /scale the loss scale.
            denom = float(n * accum)
            d = denom if scale is None else denom * scale
            need_sq = clip is not None or telemetry
            sq_loc = sq_rep = None
            if use_fused:
                # One read of each shard yields BOTH the unscaled grad
                # and its f32 squared-norm contribution (the unfused
                # path reads the shard again in split_sq below).
                sq_loc = jnp.zeros((), jnp.float32)
                sq_rep = jnp.zeros((), jnp.float32)
                unscaled = []
                for i, g in enumerate(g_leaves):
                    g_u, s = unscale_sqsum(g, d, compute_sq=need_sq)
                    unscaled.append(g_u)
                    if need_sq:
                        sq_loc, sq_rep = (
                            (sq_loc + s, sq_rep) if plan.sharded[i]
                            else (sq_loc, sq_rep + s)
                        )
                g_leaves = unscaled
            else:
                g_leaves = [g / d for g in g_leaves]

            # (3) this replica's parameter shards (dim-0 block at its
            # axis index), moments arrive pre-sharded via in_specs.
            idx = col.axis_index("data")
            p_mixed = []
            for i, p in enumerate(jax.tree.leaves(state.params)):
                if plan.sharded[i]:
                    blocks = p.reshape((n, p.shape[0] // n) + p.shape[1:])
                    p_mixed.append(
                        lax.dynamic_index_in_dim(
                            blocks, idx, axis=0, keepdims=False
                        )
                    )
                else:
                    p_mixed.append(p)
            params_mixed = jax.tree.unflatten(g_def, p_mixed)
            grads_mixed = jax.tree.unflatten(g_def, g_leaves)

            g_sq = None
            factor = None
            if need_sq:
                if use_fused:
                    loc, rep = sq_loc, sq_rep
                else:
                    loc, rep = split_sq(g_leaves)
                g_sq = col.psum(loc, "data") + rep
            if clip is not None:
                # optax.clip_by_global_norm math over the TRUE global
                # norm (the chained optax clip would see one shard).
                gnorm = jnp.sqrt(g_sq)
                factor = clip / jnp.maximum(gnorm, clip)
                if not use_fused:
                    grads_mixed = jax.tree.map(
                        lambda g: g * factor, grads_mixed
                    )

            if use_fused:
                # Fused tail: clip ×, Adam moments, bias corrections,
                # schedule step, lr_scale and the param write in ONE
                # pass per leaf shard; opt_state rebuilt in optax's
                # exact chain(identity, adam(schedule)) structure, so
                # checkpoints and the guard's where-selects are
                # untouched.  The clip factor folds into the kernel
                # instead of a separate grads multiply.
                _e, (adam_st, sched_st) = state.opt_state
                count_inc, bc1, bc2, step_size, sched_inc = adam_scalars(
                    adam_st.count, sched_st.count, lr_sched
                )
                outs = [
                    fused_adam_update(
                        g, p, mu, nu, bc1=bc1, bc2=bc2,
                        step_size=step_size, lr_scale=lr_scale,
                        factor=factor,
                    )
                    for g, p, mu, nu in zip(
                        jax.tree.leaves(grads_mixed),
                        jax.tree.leaves(params_mixed),
                        jax.tree.leaves(adam_st.mu),
                        jax.tree.leaves(adam_st.nu),
                    )
                ]
                new_params_mixed = jax.tree.unflatten(
                    g_def, [o[0] for o in outs]
                )
                new_opt = (
                    optax.EmptyState(),
                    (
                        optax.ScaleByAdamState(
                            count=count_inc,
                            mu=jax.tree.unflatten(
                                g_def, [o[1] for o in outs]
                            ),
                            nu=jax.tree.unflatten(
                                g_def, [o[2] for o in outs]
                            ),
                        ),
                        optax.ScaleByScheduleState(count=sched_inc),
                    ),
                )
                updates = jax.tree.unflatten(g_def, [o[3] for o in outs])
            else:
                updates, new_opt = tx.update(
                    grads_mixed, state.opt_state, params_mixed
                )
                updates = jax.tree.map(lambda u: u * lr_scale, updates)
                new_params_mixed = optax.apply_updates(params_mixed, updates)

            new_skipped, new_streak = state.skipped_steps, state.bad_streak
            replace_kwargs = {}
            raw_loss = loss
            if guard:
                ok = jnp.isfinite(loss)
                for g in jax.tree.leaves(grads_mixed):
                    ok = ok & jnp.all(jnp.isfinite(g))
                # Global consensus: a non-finite value lives only in the
                # shard of the replica that owns it — every replica must
                # take the same skip decision.
                ok = col.psum(jnp.where(ok, 1.0, 0.0), "data") > (n - 0.5)

                def sel(new, old):
                    return jax.tree.map(
                        lambda a, b: jnp.where(ok, a, b), new, old
                    )

                new_params_mixed = sel(new_params_mixed, params_mixed)
                new_opt = sel(new_opt, state.opt_state)
                one = jnp.ones((), jnp.int32)
                zero = jnp.zeros((), jnp.int32)
                new_skipped = state.skipped_steps + jnp.where(ok, zero, one)
                new_streak, replace_kwargs = self._scale_streak_updates(
                    state, ok, cfg, one, zero
                )
                loss = jnp.where(ok, loss, jnp.zeros_like(loss))
                metric_val = jnp.where(
                    ok, metric_val, jnp.zeros_like(metric_val)
                )
            # (4) fresh weights: bucketed all-gather of the (guarded)
            # shards back to the full replicated tree.
            full_leaves = bucketed_all_gather(
                jax.tree.leaves(new_params_mixed), plan, full_shapes, "data"
            )
            new_params = jax.tree.unflatten(g_def, full_leaves)
            new_ema = (
                jax.tree.map(
                    lambda e, p: ema_decay * e + (1.0 - ema_decay) * p,
                    state.ema_params, new_params,
                )
                if ema_decay is not None else state.ema_params
            )
            if guard and ema_decay is not None:
                new_ema = jax.tree.map(
                    lambda a, b: jnp.where(ok, a, b),
                    new_ema, state.ema_params,
                )
            new_state = state.replace(
                step=state.step + 1,
                params=new_params,
                opt_state=new_opt,
                batch_stats=state.batch_stats,
                rng=rng,
                ema_params=new_ema,
                skipped_steps=new_skipped,
                bad_streak=new_streak,
                **replace_kwargs,
            )
            if telemetry:
                u_loc, u_rep = split_sq(jax.tree.leaves(updates))
                un = jnp.sqrt(col.psum(u_loc, "data") + u_rep)
                pn = _global_norm(new_params)
                stats = {
                    "loss_raw": jnp.asarray(raw_loss, jnp.float32),
                    "grad_norm": jnp.sqrt(g_sq),
                    "param_norm": pn,
                    "update_norm": un,
                    "update_ratio": un / (pn + 1e-12),
                }
                return new_state, loss, metric_val, stats
            return new_state, loss, metric_val

        state_specs = jax.tree.map(lambda sh: sh.spec, self._state_shardings)
        batch_spec = self._batch_sharding.spec
        scalar_spec = P()
        out_specs = (
            (state_specs, scalar_spec, scalar_spec, scalar_spec)
            if telemetry else (state_specs, scalar_spec, scalar_spec)
        )
        mapped = shard_map(
            body, mesh=mesh,
            in_specs=(state_specs, batch_spec, batch_spec, scalar_spec),
            out_specs=out_specs,
            # Outputs declared P() are replicated by construction (the
            # all-gathered weights and pmean'd scalars are identical on
            # every replica); the checker cannot prove it through the
            # where-selects, so it is off.
            check_rep=False,
        )

        def sharded_train_step(state, x, y, lr_scale):
            return mapped(state, x, y, lr_scale)

        return sharded_train_step

    def _make_eval_step(self, module, takes_train, has_bs, multi=False):
        """Compiled eval step for ``module``; with ``multi`` also returns
        the K-batches-per-dispatch variant (scan), else None.  Pure — no
        trainer state is touched (test() builds steps for foreign modules
        through this too)."""
        criterion, metric_fn = self.criterion, self.metric_fn
        takes_targets = _module_takes_targets(module)
        compute_dtype = self._compute_dtype
        if compute_dtype is not None:
            from ml_trainer_tpu.precision import cast_floating
        if takes_targets and metric_fn is not None:
            # The constructor guard only covers the trainer's own model;
            # test() evaluates foreign modules through here too, and a
            # fabricated 0.0 metric must not masquerade as a measurement.
            raise ValueError(
                "metric must be None when evaluating a model that computes "
                "its own loss (its forward returns a scalar, not logits)"
            )

        def eval_step(variables, x, y):
            kwargs = {"train": False} if takes_train else {}
            if compute_dtype is not None:
                # Same policy as training: compute in bf16 against the
                # fp32 masters, score losses/metrics in fp32.
                variables = dict(
                    variables,
                    params=cast_floating(variables["params"], compute_dtype),
                )
                if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
                    x = jnp.asarray(x).astype(compute_dtype)
            if takes_targets:
                # Self-loss model: the forward returns the scalar loss
                # (metric is None for these — validated at construction).
                loss = module.apply(variables, x, targets=y, **kwargs)
                if compute_dtype is not None:
                    loss = loss.astype(jnp.float32)
                return loss, jnp.zeros(())
            out = module.apply(variables, x, **kwargs)
            if compute_dtype is not None:
                out = out.astype(jnp.float32)
            loss = criterion(out, y)
            metric_val = (
                metric_fn(out, y) if metric_fn is not None else jnp.zeros(())
            )
            return loss, metric_val

        eval_multi = None
        if multi:
            def eval_multi_fn(variables, xs, ys):
                def body(_, xy):
                    return 0, eval_step(variables, *xy)

                _, (losses, metrics) = jax.lax.scan(body, 0, (xs, ys))
                return losses.sum(), metrics.sum()

            eval_multi = jax.jit(eval_multi_fn)
        return jax.jit(eval_step), eval_multi

    def _state_variables(self, ema: Optional[bool] = None) -> dict:
        """Inference-time variables.  With ``ema_decay`` set the EMA weights
        are the model's public face (eval/test/save); pass ``ema=False`` for
        the raw training weights."""
        use_ema = self.ema_decay is not None if ema is None else ema
        params = (
            self.state.ema_params
            if use_ema and self.state.ema_params is not None
            else self.state.params
        )
        variables = {"params": params}
        if self._has_batch_stats:
            variables["batch_stats"] = self.state.batch_stats
        return variables

    def _postfix_metric(self, metric_sum, seen: int, n: int) -> float:
        """Progress-bar metric value.  Linear metrics keep the reference's
        running-average-over-full-epoch display quirk
        (ref: src/trainer.py:193-194); metrics with an epoch finalizer
        must divide by the batches actually SEEN before finalizing —
        exponentiating a partial sum over the full count would display a
        number with no interpretation (it would climb from ~exp(0) all
        epoch)."""
        if getattr(self.metric_fn, "finalize", None) is not None:
            return self._metric_finalize(float(metric_sum) / max(seen, 1))
        return float(metric_sum) / n

    # ------------------------------------------------------------------ loops
    def _train_one_epoch(self, epoch: int) -> None:
        self.train_loader.set_epoch(epoch - 1)
        n = len(self.train_loader)
        loss_sum = jnp.zeros(())
        metric_sum = jnp.zeros(())
        epoch_t0 = time.time()
        lr_scale = jnp.asarray(self._lr_scale, jnp.float32)
        if self.steps_per_execution > 1:
            loss_sum, metric_sum = self._train_one_epoch_multi(
                epoch, n, lr_scale
            )
            if self._preempt_requested:
                # Multi-step dispatch has no per-batch cursor: no
                # emergency mid-epoch save — resume restarts from the
                # last epoch-boundary checkpoint (documented trade).
                self.preempted = True
                return
        else:
            start_b = 0
            mid, self._resume_mid = self._resume_mid, None
            if mid is not None and int(mid["epoch"]) == epoch:
                # Mid-epoch resume: SKIP the batches the interrupted run
                # already trained on.  Skipping still consumes them from
                # the loader (the augmentation rng advances identically),
                # so the remaining steps see exactly the batches the
                # uninterrupted run would — bit-exact continuation.
                start_b = int(mid["batches_done"])
                # mid[...] is the resume manifest — host JSON, no sync.
                # graft-lint: host-value
                loss_sum = jnp.asarray(float(mid["loss_sum"]), jnp.float32)
                metric_sum = jnp.asarray(
                    float(mid["metric_sum"]), jnp.float32  # graft-lint: host-value
                )
                self._skipped_base = int(mid.get("skipped_base", 0))
                logger.info(
                    f"Mid-epoch resume: epoch {epoch} continues at batch "
                    f"{start_b + 1}/{n}."
                )
            it = iter(self.train_loader)
            for _ in range(start_b):
                next(it)
            from ml_trainer_tpu.resilience import faults

            plan = faults.active_plan()
            batches = prefetch_to_device(
                it, size=2, sharding=self._batch_sharding
            )
            with tqdm(
                batches, total=n, initial=start_b, unit="batch"
            ) as tepoch:
                stats = None
                for i, (x, y) in enumerate(tepoch):
                    done = start_b + i + 1  # 1-based batch cursor
                    # 1-based global train step ((epoch-1)*steps_per_epoch
                    # + batch) — pure host arithmetic, no device sync;
                    # the fault-injection AND telemetry step coordinate.
                    gstep = (epoch - 1) * n + done
                    if plan is not None:
                        if plan.fire("preempt", step=gstep) is not None:
                            self._request_preemption("injected preempt")
                        if plan.fire("nan_grad", step=gstep) is not None:
                            x = self._poison_batch(x)
                        self._poll_host_faults(plan, gstep)
                    out = self._train_step(self.state, x, y, lr_scale)
                    self.state, loss, metric_val = out[0], out[1], out[2]
                    if self.telemetry:
                        stats = out[3]
                    loss_sum = loss_sum + loss
                    metric_sum = metric_sum + metric_val
                    if self._profile_hook:
                        self._profiler.on_step(gstep)
                    if done % self.log_every == 0 or done == n:
                        # The only host syncs in the epoch (the reference
                        # pays one per batch, ref: src/trainer.py:186).
                        # Display matches the reference's running-average-
                        # over-full-epoch quirk (ref: src/trainer.py:193-194).
                        if self.metric:
                            tepoch.set_postfix(
                                loss=float(loss_sum) / n,  # graft-lint: sync-ok
                                metric=self._postfix_metric(
                                    metric_sum, done, n
                                ),
                            )
                        else:
                            # graft-lint: sync-ok (the log_every fence)
                            tepoch.set_postfix(loss=float(loss))
                        if self._telemetry is not None and stats is not None:
                            self._telemetry.on_sync(
                                gstep, stats, epoch=epoch,
                                skipped_total=self._skipped_now(),
                                lr_scale=self._lr_scale,
                                loss_scale=self._loss_scale_now(),
                            )
                        if self._maybe_rollback(gstep):
                            lr_scale = jnp.asarray(
                                self._lr_scale, jnp.float32
                            )
                    if (
                        self.desync_every_steps
                        and process_count() > 1
                        and gstep % self.desync_every_steps == 0
                    ):
                        # Step-granular desync forensics: same gstep on
                        # every host (loaders are length-identical), so
                        # all hosts enter the broadcast together.
                        from ml_trainer_tpu.parallel.desync import (
                            check_desync,
                        )

                        check_desync(
                            self.state.params, step=gstep,
                            flight=self._flight,
                        )
                    if (
                        self.save_every_steps
                        and done % self.save_every_steps == 0
                        and done < n
                    ):
                        self._save_mid_epoch(
                            epoch, done, loss_sum, metric_sum
                        )
                    if self._preempt_requested:
                        # The in-flight step finished above; emergency
                        # checkpoint with the batch cursor, then exit.
                        self._save_mid_epoch(
                            epoch, done, loss_sum, metric_sum
                        )
                        ckpt.wait_for_checkpoints()
                        self._preempt_info = {
                            "epoch": epoch, "batches_done": done,
                        }
                        self.preempted = True
                        break
                    if self._reshape_request is not None:
                        # Elastic drain: the in-flight step committed;
                        # emergency-checkpoint the cursor (crash safety
                        # while the mesh is being rebuilt), then hand
                        # the reshape to _fit's loop.
                        self._save_mid_epoch(
                            epoch, done, loss_sum, metric_sum
                        )
                        ckpt.wait_for_checkpoints()
                        req, self._reshape_request = (
                            self._reshape_request, None
                        )
                        self._reshape_pending = {
                            "request": req,
                            "epoch": epoch,
                            "step": gstep,
                            "batches_done": done,
                            # The drain fence: the in-flight step must
                            # land before the mesh is rebuilt.
                            "loss_sum": float(loss_sum),  # graft-lint: sync-ok
                            "metric_sum": float(metric_sum),  # graft-lint: sync-ok
                        }
                        break
            if self.preempted or self._reshape_pending is not None:
                return  # partial epoch: no history entry yet
        # float(loss_sum) above fenced the device work, so this timestamp
        # covers actual execution, not async dispatch.
        self.train_losses.append(float(loss_sum) / n)  # graft-lint: sync-ok
        if self.state.skipped_steps is not None:
            # graft-lint: sync-ok (epoch-boundary counter fetch)
            cum = int(jax.device_get(self.state.skipped_steps))
            self.skipped_steps.append(cum - self._skipped_base)
            self._skipped_base = cum
        dt = time.time() - epoch_t0
        logger.info(
            f"Epoch {epoch}: {n * self.global_batch / max(dt, 1e-9):,.0f} "
            f"samples/s ({dt:.1f}s, global batch {self.global_batch})"
        )
        if self.metric:
            self.train_metrics.append(
                self._metric_finalize(float(metric_sum) / n)  # graft-lint: sync-ok
            )

    def _train_one_epoch_multi(self, epoch: int, n: int, lr_scale):
        """Epoch driven K optimizer steps per dispatch: full chunks of
        ``steps_per_execution`` batches go through the scanned program, the
        ragged tail through the per-batch step — same trajectory either
        way."""
        k = self.steps_per_execution
        loss_sum = jnp.zeros(())
        metric_sum = jnp.zeros(())
        tail: list = []  # ragged final batches, filled once chunks() drains

        stacked = prefetch_to_device(
            _chunk_batches(self.train_loader, k, tail),
            size=2, sharding=self._stacked_sharding,
        )
        with tqdm(total=n, unit="batch") as tepoch:
            done = 0

            def log(step_n, loss, stats):
                if done % max(self.log_every, k) < step_n or done == n:
                    if self.metric:
                        tepoch.set_postfix(
                            loss=float(loss_sum) / n,  # graft-lint: sync-ok
                            metric=self._postfix_metric(metric_sum, done, n),
                        )
                    else:
                        # Mean loss of the last dispatch — the multi-step
                        # analog of the single-step path's last-batch loss.
                        # graft-lint: sync-ok (per-dispatch fence)
                        tepoch.set_postfix(loss=float(loss) / step_n)
                    if self._telemetry is not None and stats is not None:
                        self._telemetry.on_sync(
                            (epoch - 1) * n + done, stats, epoch=epoch,
                            skipped_total=self._skipped_now(),
                            lr_scale=self._lr_scale,
                            loss_scale=self._loss_scale_now(),
                        )

            for xs, ys in stacked:
                out = self._train_multi_step(self.state, xs, ys, lr_scale)
                self.state, loss, metric_val = out[0], out[1], out[2]
                stats = out[3] if self.telemetry else None
                loss_sum = loss_sum + loss
                metric_sum = metric_sum + metric_val
                done += k
                if self._profile_hook:
                    self._profiler.on_step((epoch - 1) * n + done)
                tepoch.update(k)
                log(k, loss, stats)
                self._maybe_check_desync(epoch, n, done, k)
                if self._preempt_requested:
                    return loss_sum, metric_sum
            for x, y in prefetch_to_device(
                iter(tail), size=2, sharding=self._batch_sharding
            ):
                out = self._train_step(self.state, x, y, lr_scale)
                self.state, loss, metric_val = out[0], out[1], out[2]
                stats = out[3] if self.telemetry else None
                loss_sum = loss_sum + loss
                metric_sum = metric_sum + metric_val
                done += 1
                tepoch.update(1)
                log(1, loss, stats)
                self._maybe_check_desync(epoch, n, done, 1)
                if self._preempt_requested:
                    return loss_sum, metric_sum
        return loss_sum, metric_sum

    def _maybe_check_desync(self, epoch: int, n: int, done: int,
                            step_n: int) -> None:
        """Multi-step-path desync cadence: fire when a multiple of
        ``desync_every_steps`` landed inside the last dispatch of
        ``step_n`` steps.  ``done`` is host-deterministic, so every host
        joins the broadcast at the same dispatch."""
        if (
            self.desync_every_steps
            and process_count() > 1
            and done % self.desync_every_steps < step_n
        ):
            from ml_trainer_tpu.parallel.desync import check_desync

            check_desync(
                self.state.params, step=(epoch - 1) * n + done,
                flight=self._flight,
            )

    def _validate_one_epoch(self) -> None:
        n = len(self.val_loader)
        loss_sum = jnp.zeros(())
        metric_sum = jnp.zeros(())
        variables = self._state_variables()
        k = self.steps_per_execution
        if k > 1:
            tail: list = []
            with tqdm(total=n, unit="batch") as tepoch:
                done = 0

                def log(step_n, loss):
                    if done % max(self.log_every, k) < step_n or done == n:
                        if self.metric:
                            tepoch.set_postfix(
                                loss=float(loss_sum) / n,
                                metric=self._postfix_metric(metric_sum, done, n),
                            )
                        else:
                            # Mean loss of the last dispatch — the analog of
                            # the single-step path's last-batch loss.
                            tepoch.set_postfix(loss=float(loss) / step_n)

                for xs, ys in prefetch_to_device(
                    _chunk_batches(self.val_loader, k, tail),
                    size=2, sharding=self._stacked_sharding,
                ):
                    loss, metric_val = self._eval_multi_step(variables, xs, ys)
                    loss_sum = loss_sum + loss
                    metric_sum = metric_sum + metric_val
                    done += k
                    tepoch.update(k)
                    log(k, loss)
                for x, y in prefetch_to_device(
                    iter(tail), size=2, sharding=self._batch_sharding
                ):
                    loss, metric_val = self._eval_step(variables, x, y)
                    loss_sum = loss_sum + loss
                    metric_sum = metric_sum + metric_val
                    done += 1
                    tepoch.update(1)
                    log(1, loss)
        else:
            batches = prefetch_to_device(
                self.val_loader, size=2, sharding=self._batch_sharding
            )
            with tqdm(batches, total=n, unit="batch") as tepoch:
                for i, (x, y) in enumerate(tepoch):
                    loss, metric_val = self._eval_step(variables, x, y)
                    loss_sum = loss_sum + loss
                    metric_sum = metric_sum + metric_val
                    if (i + 1) % self.log_every == 0 or (i + 1) == n:
                        if self.metric:
                            tepoch.set_postfix(
                                loss=float(loss_sum) / n,
                                metric=self._postfix_metric(metric_sum, i + 1, n),
                            )
                        else:
                            tepoch.set_postfix(loss=float(loss))
        self.val_losses.append(float(loss_sum) / n)
        if self.metric:
            self.val_metrics.append(self._metric_finalize(float(metric_sum) / n))

    # ------------------------------------------------------------------- fit
    def fit(self, resume: bool = False) -> None:
        """Full training run (ref: src/trainer.py:243-275).  ``resume=True``
        restarts from the latest full checkpoint — a capability the
        reference lacks (SURVEY.md §5).  With ``handle_preemption`` (the
        default) SIGTERM/SIGINT trigger a clean preemption exit: finish
        the in-flight step, write an emergency checkpoint + exit marker,
        return with ``self.preempted = True``; ``fit(resume=True)`` then
        continues where the signal landed (bit-exactly mid-epoch when
        ``save_every_steps`` semantics apply)."""
        self.preempted = False
        self._preempt_requested = False
        self._preempt_info: Optional[dict] = None
        self._reshape_request = None
        self._reshape_pending = None
        prev_handlers = self._install_preempt_handlers()
        try:
            self._fit(resume)
        except Exception as e:
            # Crash forensics: the last N step records + the error, on
            # disk before the exception unwinds the process — followed by
            # a best-effort run report so the post-mortem starts from the
            # distilled numbers, not raw logs.
            self._flight.dump(
                "unhandled_exception", out_dir=self._flight_dir(),
                error=f"{type(e).__name__}: {e}",
            )
            self._write_run_report(f"crash: {type(e).__name__}: {e}")
            raise
        finally:
            self._restore_preempt_handlers(prev_handlers)
            if self.telemetry:
                # The recompile invariant is a property of THIS run's
                # steady state; whatever compiles after fit() returns
                # (test(), predict(), another trainer) is legitimate.
                from ml_trainer_tpu.telemetry import compile_watch

                compile_watch.mark_cold()

    def _install_preempt_handlers(self):
        if not self.handle_preemption:
            return {}
        import signal

        prev = {}
        try:
            for sig in (signal.SIGTERM, signal.SIGINT):
                prev[sig] = signal.signal(sig, self._on_preempt_signal)
        except ValueError:
            # Not the main thread: signals cannot be installed here; the
            # injected `preempt` fault path still works.
            return prev
        return prev

    def _restore_preempt_handlers(self, prev) -> None:
        import signal

        for sig, handler in prev.items():
            try:
                signal.signal(sig, handler)
            except (ValueError, TypeError):
                pass

    def _on_preempt_signal(self, signum, frame) -> None:
        self._request_preemption(f"signal {signum}")

    def _request_preemption(self, reason: str) -> None:
        if not self._preempt_requested:
            logger.warning(
                f"Preemption requested ({reason}): finishing the in-flight "
                "step, then writing an emergency checkpoint."
            )
        self._preempt_requested = True

    def _fit(self, resume: bool) -> None:
        logger.info("Start training..")
        start_epoch = 1
        ckpt_dir = os.path.join(self.model_dir, "checkpoints")
        if self.telemetry:
            # Goodput window: anchored here so every bucket (and the
            # compute remainder) is charged against THIS run's wall
            # clock; compile warmup re-opens for the programs this fit
            # legitimately builds (closed after the first epoch below).
            from ml_trainer_tpu.telemetry import compile_watch

            compile_watch.mark_cold()
            if self._telemetry is not None:
                self._telemetry.goodput.start()
        if resume:
            start_epoch = self._resume_from_latest(ckpt_dir)
        self._mark_warm_after_epoch = True
        for epoch in range(start_epoch, self.epochs + 1):
            # Checked at loop entry so a resumed run that comes back
            # already out of patience stops BEFORE training (and
            # overwriting the exported weights with) a wasted epoch.
            if self._out_of_patience():
                break
            logger.info(f"{'-' * 30} EPOCH {epoch} / {self.epochs} {'-' * 30}")
            self._train_one_epoch(epoch)
            while self._reshape_pending is not None:
                # Elastic reshape: the epoch drained mid-flight; rebuild
                # the mesh around the lost host and re-enter the SAME
                # epoch at the saved cursor (resilience/elastic.py).
                self._perform_reshape()
                self._train_one_epoch(epoch)
            if self.preempted:
                self._write_preempt_marker(ckpt_dir)
                self._flight.record(
                    "preemption", **(self._preempt_info or {"epoch": epoch})
                )
                self._flight.dump(
                    "preemption", out_dir=self._flight_dir(),
                    **(self._preempt_info or {"epoch": epoch}),
                )
                logger.warning(
                    "Preempted: emergency checkpoint committed; exiting "
                    "fit() cleanly (resume with fit(resume=True))."
                )
                break
            self.clear()
            self._validate_one_epoch()
            self.clear()
            if self._mark_warm_after_epoch:
                # Every program a steady-state epoch needs (train + eval,
                # full and ragged-tail shapes) has now compiled: any
                # compile from here on is a recompile incident the watch
                # records with flight forensics.  An elastic reshape
                # re-arms this flag — the reshaped mesh legitimately
                # compiles fresh programs for one epoch.
                self._mark_warm_after_epoch = False
                if self.telemetry:
                    from ml_trainer_tpu.telemetry import compile_watch

                    compile_watch.mark_warm()
            if self._plateau is not None:
                self._lr_scale = self._plateau.update(self.val_losses[-1])
            # Every host computes the same val loss, so `improved` (and the
            # stop decision) is globally consistent without a collective.
            improved = self.val_losses[-1] < self._best_val
            if improved:
                self._best_val = self.val_losses[-1]
                self._bad_epochs = 0
            else:
                self._bad_epochs += 1
            if process_count() > 1:
                # Cross-host replica-desync check (the "race detector",
                # SURVEY.md §5) — one scalar over DCN per epoch.
                from ml_trainer_tpu.parallel.desync import check_desync

                check_desync(
                    self.state.params, step=epoch * self.steps_per_epoch,
                    flight=self._flight,
                )
            if self._cluster is not None:
                # Cluster heartbeat aggregation: one tiny allgather per
                # epoch, every host at the same program point (the same
                # collective discipline as check_desync above).  After it,
                # host 0's /metrics and JSONL sink carry cluster_* series
                # for the whole pod.
                self._cluster.sync(step=epoch * self.steps_per_epoch)
            if self._reshape_request is not None:
                # Boundary reshape (a straggler verdict from the
                # epoch-end aggregation): the epoch is complete, so no
                # mid-epoch cursor carries over — the next epoch starts
                # on the reshaped mesh.
                req, self._reshape_request = self._reshape_request, None
                self._reshape_pending = {
                    "request": req, "epoch": epoch,
                    "step": epoch * self.steps_per_epoch,
                    "batches_done": None, "loss_sum": 0.0,
                    "metric_sum": 0.0,
                }
                self._perform_reshape()
            # Save on the primary host only (ref: src/trainer.py:252-254).
            # When params are genuinely PARTITIONED across hosts (TP/FSDP
            # multi-host), the fetch is a global allgather — a collective —
            # so every host must join it, or host 0 blocks in a gather the
            # others never enter (they'd already be in the v3 commit
            # barrier below).  Replicated params fetch locally and keep
            # the export primary-only.
            variables = self._state_variables()
            export_is_collective = process_count() > 1 and any(
                not leaf.is_fully_addressable
                and not getattr(leaf, "is_fully_replicated", False)
                for leaf in jax.tree.leaves(variables)
            )
            host_vars = (
                ckpt.fetch_to_host(variables)
                if (is_primary() or export_is_collective) else None
            )
            from ml_trainer_tpu.telemetry import goodput
            from ml_trainer_tpu.telemetry.spans import span

            if is_primary():
                logger.info("Saving the model.")
                from flax import serialization

                # One device fetch + serialization covers both exports
                # (the best/ copy is the same bytes on improving epochs).
                with span("model_export", epoch=epoch), \
                        goodput.timed("ckpt_stall"):
                    data = serialization.to_bytes(host_vars)
                    ckpt.write_model_bytes(self.model_dir, data)
                    # The export manifest carries the weights fingerprint
                    # a serving deploy keys KV portability on
                    # (docs/serving.md "Deploys").
                    ckpt.write_model_manifest(
                        self.model_dir, host_vars, data=data
                    )
                    if improved and self.save_best:
                        ckpt.write_model_bytes(
                            os.path.join(self.model_dir, "best"), data
                        )
                        ckpt.write_model_manifest(
                            os.path.join(self.model_dir, "best"),
                            host_vars, data=data,
                        )
            if self._sharded_ckpt:
                # COLLECTIVE: every process contributes its addressable
                # shards; no host gathers the full state (format v3).
                with span("ckpt_write", epoch=epoch, sharded=True), \
                        goodput.timed("ckpt_stall"):
                    ckpt.save_checkpoint_sharded(
                        ckpt_dir, self.state, self._partial_history(), epoch,
                        block=False,
                    )
            elif is_primary():
                # Async: the write lands on the background writer thread
                # while the next epoch trains (jax arrays are immutable, so
                # the snapshot is consistent); fit-end joins the queue.
                # The span covers the enqueue (the host-blocking part).
                with span("ckpt_write", epoch=epoch, sharded=False), \
                        goodput.timed("ckpt_stall"):
                    ckpt.save_checkpoint(
                        ckpt_dir, self.state, self._partial_history(), epoch,
                        block=False,
                    )
            if self.metric:
                logger.info(
                    f"train loss: {self.train_losses[-1]} - "
                    f"train {self.metric}: {self.train_metrics[-1]}"
                )
                logger.info(
                    f"valid loss: {self.val_losses[-1]} - "
                    f"valid {self.metric}: {self.val_metrics[-1]}\n\n"
                )
            else:
                logger.info(f"train loss: {self.train_losses[-1]}")
                logger.info(f"valid loss: {self.val_losses[-1]}\n\n")
            if self._out_of_patience():
                break
        self.history = {
            "epochs": [*range(1, len(self.train_losses) + 1)],
            "train_loss": self.train_losses,
            "val_loss": self.val_losses,
            "train_metric": self.train_metrics,
            "val_metric": self.val_metrics,
            "metric_type": self.metric,
            # Per-epoch count of steps the on-device all-finite guard
            # skipped (all zeros on a healthy run), the number of
            # rollback-to-last-good events, and the elastic mesh
            # reshapes survived — the resilience ledger.
            "skipped_steps": self.skipped_steps,
            "rollbacks": self.rollbacks,
            "reshapes": self.reshapes,
        }
        if self.save_history and is_primary():
            self.save_history_(self.model_dir)
        from ml_trainer_tpu.telemetry import goodput

        with goodput.timed("ckpt_stall"):
            ckpt.wait_for_checkpoints()
        self._write_run_report("preempted" if self.preempted else "completed")
        logger.info("Training Complete.")

    def _out_of_patience(self) -> bool:
        stop = (
            self.early_stop_patience is not None
            and self._bad_epochs >= self.early_stop_patience
        )
        if stop:
            logger.info(
                f"Early stop: no val-loss improvement in "
                f"{self._bad_epochs} epochs (best {self._best_val:.6f})."
            )
        return stop

    def _partial_history(self) -> dict:
        h = {
            "train_loss": self.train_losses,
            "val_loss": self.val_losses,
            "train_metric": self.train_metrics,
            "val_metric": self.val_metrics,
            "metric_type": self.metric,
            "lr_scale": self._lr_scale,
            "skipped_steps": self.skipped_steps,
            "rollbacks": self.rollbacks,
            "reshapes": self.reshapes,
        }
        if self._plateau is not None:
            h["plateau"] = {
                "best": self._plateau.best,
                "num_bad_epochs": self._plateau.num_bad_epochs,
                "scale": self._plateau.scale,
            }
        h["early_stop"] = {
            "best_val": self._best_val, "bad_epochs": self._bad_epochs,
        }
        return h

    def _apply_resume_scalars(self, saved: dict) -> None:
        """Re-install the host-side training scalars from a restored
        checkpoint's history dict (no broadcast — the caller guarantees
        every host sees identical ``saved``, e.g. via shared storage).
        The v2 multi-host resume path keeps its own inline scalar
        re-install: there the non-primary hosts have no ``saved`` dict and
        the values must travel by broadcast instead."""
        self.train_losses = list(saved.get("train_loss", []))
        self.val_losses = list(saved.get("val_loss", []))
        self.train_metrics = list(saved.get("train_metric", []))
        self.val_metrics = list(saved.get("val_metric", []))
        self.skipped_steps = list(saved.get("skipped_steps", []))
        self.rollbacks = int(saved.get("rollbacks", 0))
        self.reshapes = list(saved.get("reshapes", []))
        self._lr_scale = float(saved.get("lr_scale", 1.0))
        plateau = saved.get("plateau", {})
        if self._plateau is not None:
            self._plateau.best = float(plateau.get("best", np.inf))
            self._plateau.num_bad_epochs = int(plateau.get("num_bad_epochs", 0))
            self._plateau.scale = float(plateau.get("scale", 1.0))
        early = saved.get("early_stop", {})
        self._best_val = float(early.get("best_val", np.inf))
        self._bad_epochs = int(early.get("bad_epochs", 0))

    # ------------------------------------------------------------ resilience
    def _poll_host_faults(self, plan, gstep: int) -> None:
        """``host_kill`` / ``host_hang`` injection (resilience/faults.py).

        Multi-process: the MATCHING worker is the failing host — it
        hard-exits (kill: the SIGKILL'd pod host, no emergency
        checkpoint) or stalls (hang: a real straggler for the cluster
        telemetry to catch).  Single-process simulated cluster: the
        fault names a simulated host and the elastic controller drains
        and reshapes around it (without ``elastic=`` the fault degrades
        to a preemption request — the restart path)."""
        for kind in ("host_kill", "host_hang"):
            fault = plan.fire(kind, step=gstep)
            if fault is None:
                continue
            if process_count() > 1:
                if int(fault.host) == process_index():
                    if kind == "host_kill":
                        logger.error(
                            f"host_kill fault: host {fault.host} "
                            f"hard-exiting at step {gstep} (no emergency "
                            "checkpoint — the SIGKILL'd-host case)"
                        )
                        os._exit(113)
                    logger.warning(
                        f"host_hang fault: host {fault.host} stalling "
                        f"{fault.secs}s at step {gstep}"
                    )
                    time.sleep(float(fault.secs))
                continue
            if self.elastic is None:
                logger.warning(
                    f"{kind} fault without Trainer(elastic=...): treating "
                    "as a preemption (emergency checkpoint + clean exit)"
                )
                self._request_preemption(f"{kind} fault")
                continue
            self._request_reshape(kind, int(fault.host), step=gstep)

    def _on_straggler_verdict(self, *, host: int, factor: float,
                              step=None) -> None:
        """Straggler verdict from ``telemetry/cluster.py``: past the
        elastic reshape factor, request a drain+reshape around the
        straggling host (pure alarm otherwise)."""
        cfg = self.elastic
        if cfg is None or cfg.straggler_reshape_factor is None:
            return
        if factor >= cfg.straggler_reshape_factor:
            self._request_reshape(
                "straggler", int(host), step=step,
                detail={"factor": round(float(factor), 2)},
            )

    def _request_reshape(self, trigger: str, lost_host: int, step=None,
                         detail: Optional[dict] = None) -> None:
        """Queue one drain→reshape; consumed after the in-flight step."""
        from ml_trainer_tpu.resilience.elastic import ReshapeRequest

        if self.elastic is None or process_count() > 1:
            return
        if lost_host not in self._live_hosts:
            logger.warning(
                f"reshape request for host {lost_host} ignored: already "
                f"removed (live hosts {self._live_hosts})"
            )
            return
        if len(self._live_hosts) - 1 < self.elastic.min_hosts or (
            len(self.reshapes) >= self.elastic.max_reshapes
        ):
            logger.warning(
                f"reshape around host {lost_host} refused "
                f"(live={len(self._live_hosts)}, "
                f"min_hosts={self.elastic.min_hosts}, "
                f"reshapes={len(self.reshapes)}/"
                f"{self.elastic.max_reshapes}); treating as preemption"
            )
            self._request_preemption(f"{trigger} past elastic bounds")
            return
        if self._reshape_request is None and not self._preempt_requested:
            self._reshape_request = ReshapeRequest(
                trigger=trigger, lost_host=int(lost_host),
                step=step, detail=detail or {},
            )
            logger.warning(
                f"Elastic reshape requested ({trigger}, lost host "
                f"{lost_host}): draining the in-flight step."
            )

    def _perform_reshape(self) -> None:
        """Reshape the mesh around the lost host and keep training.

        The drained cursor (``_reshape_pending``) marks where the epoch
        stopped; this rebuilds the world — validated BEFORE any device
        allocates — and re-enters the same epoch via the mid-epoch
        resume machinery:

        1. ``precheck_topology``: the analytic memory ledger prices the
           target topology (structured ``TopologyError`` if it cannot
           fit);
        2. ``remap_state_shardings`` + ``validate_reshard``: per-leaf
           target placement with the ZeRO-1 shape rule re-applied
           (structured ``ReshardError`` naming the offending axis);
        3. ONE whole-tree host fetch + ``place_tree`` placement;
        4. batch/LR policy: ``'global'`` preserves the global batch
           (math unchanged — the trajectory equals the uninterrupted
           run's); ``'per_device'`` shrinks it by the survivor ratio
           and rescales the LR linearly;
        5. compiled steps, bucket plan, memory ledger rebuilt; compile
           warmup re-opens for the reshaped programs.

        The whole recovery is charged to the goodput ``reshape`` bucket
        and recorded in ``history['reshapes']`` + a flight ``reshape``
        event (old/new topology, trigger, steps-lost)."""
        from ml_trainer_tpu.parallel import create_mesh, place_tree
        from ml_trainer_tpu.resilience import elastic as el
        from ml_trainer_tpu.telemetry import goodput

        info, self._reshape_pending = self._reshape_pending, None
        req = info["request"]
        cfg = self.elastic
        t0 = time.perf_counter()
        with goodput.timed("reshape"):
            old_topology = {a: int(s) for a, s in self.mesh.shape.items()}
            old_devices = list(self.mesh.devices.flat)
            groups = el.host_groups(old_devices, len(self._live_hosts))
            pos = self._live_hosts.index(int(req.lost_host))
            new_devices = [
                d for gi, grp in enumerate(groups)
                for d in grp if gi != pos
            ]
            new_shape = el.shrink_mesh_shape(
                old_topology, len(old_devices), len(new_devices)
            )
            old_global = self.global_batch
            new_global = old_global
            if cfg.batch_policy == "per_device":
                new_global = max(
                    old_global * len(new_devices) // len(old_devices), 1
                )
            # (1) fit check from config alone — nothing has allocated.
            el.precheck_topology(
                self.model,
                (new_global,) + tuple(self._batch_geometry[1:]),
                mesh_shape=new_shape,
                optimizer=self.optimizer_type,
                sharding_rules=self._sharding_rules,
                shard_opt_state=self._shard_opt_state,
                dp_update=self.dp_update,
                precision=(
                    self.precision.label() if self.precision.active else None
                ),
                ema=self.ema_decay is not None,
                grad_accum_steps=self.grad_accum_steps,
                batch_dtype=self._batch_dtype,
                capacity_bytes=cfg.capacity_bytes,
                margin=cfg.margin,
            )
            new_mesh = create_mesh(new_shape, devices=new_devices)
            # (2) per-leaf target placement, divisibility-validated.
            new_shardings = el.remap_state_shardings(
                self._state_shardings, self.state, new_mesh
            )
            el.validate_reshard(
                self.state, new_shardings,
                source_topology={"axes": old_topology},
            )
            # (3) one whole-tree fetch + placement.
            host_state = jax.device_get(self.state)
            self.mesh = new_mesh
            self._batch_sharding = batch_sharding(new_mesh)
            self._replicated = replicated(new_mesh)
            self._data_parallel = int(
                np.prod(
                    [
                        new_mesh.shape[a]
                        for a in ("data", "fsdp")
                        if a in new_mesh.axis_names
                    ],
                    initial=1,
                )
            )
            self.state = place_tree(host_state, new_shardings)
            self._state_shardings = new_shardings
            self._live_hosts.pop(pos)
            # (4) batch/LR policy.
            lr_before = self._lr_scale
            cursor = info.get("batches_done")
            if cfg.batch_policy == "per_device" and new_global != old_global:
                self._build_loaders(
                    self._datasets[0], self._datasets[1], new_global,
                    self.config,
                )
                self.steps_per_epoch = len(self.train_loader)
                # Linear scaling rule, in reverse: the LR follows the
                # global batch down so per-sample update magnitude holds.
                self._lr_scale *= self.global_batch / old_global
                if cursor is not None:
                    # Re-express the cursor in the new batch geometry
                    # (same shuffled sample order — the loader batches a
                    # seed-determined permutation sequentially).
                    cursor = (cursor * old_global) // self.global_batch
            # (5) rebuild the compiled programs on the new mesh.
            if self.dp_update == "sharded":
                from ml_trainer_tpu.parallel import plan_grad_buckets

                self._bucket_plan = plan_grad_buckets(
                    self.state.params, int(self.mesh.shape["data"]),
                    bucket_bytes=int(self.bucket_mb * 2 ** 20),
                )
            self._build_steps()
            if self.telemetry:
                from ml_trainer_tpu.telemetry import (
                    compile_watch,
                    memory as _memory,
                )

                # The reshaped programs legitimately compile: re-open
                # warmup (closed again after the next full epoch) and
                # re-publish the ledger for the new per-device split.
                compile_watch.mark_cold()
                self._mark_warm_after_epoch = True
                self._memory_ledger = _memory.train_ledger(self)
                self._memory_ledger.publish()
        downtime = time.perf_counter() - t0
        record = {
            "step": int(info.get("step") or 0),
            "epoch": int(info["epoch"]),
            "trigger": req.trigger,
            "lost_host": int(req.lost_host),
            "old_topology": old_topology,
            "new_topology": {a: int(s) for a, s in self.mesh.shape.items()},
            "old_global_batch": int(old_global),
            "global_batch": int(self.global_batch),
            "lr_scale": float(self._lr_scale),
            # The drain committed the in-flight step and the controller
            # continues from LIVE state: a clean reshape loses zero
            # steps (hard kills lose up to the save_every_steps cadence
            # instead — the restart path).
            "steps_lost": 0,
            "downtime_secs": round(downtime, 3),
        }
        if req.detail:
            record["detail"] = req.detail
        self.reshapes.append(record)
        self._flight.record("reshape", **record)
        if self._telemetry is not None:
            self._telemetry.registry.counter(
                "train_reshapes_total",
                "elastic mesh reshapes survived by this process",
            ).inc()
        if info.get("batches_done") is not None:
            self._resume_mid = {
                "epoch": int(info["epoch"]),
                "batches_done": int(cursor),
                "loss_sum": float(info["loss_sum"]),
                "metric_sum": float(info["metric_sum"]),
                "skipped_base": int(self._skipped_base),
            }
        logger.warning(
            f"Elastic reshape: lost host {req.lost_host} ({req.trigger}); "
            f"mesh {record['old_topology']} -> {record['new_topology']}, "
            f"global batch {old_global} -> {self.global_batch}, lr scale "
            f"{lr_before:.4g} -> {self._lr_scale:.4g}, downtime "
            f"{downtime:.2f}s."
        )

    @staticmethod
    def _poison_batch(x):
        """``nan_grad`` fault: NaN-fill a float batch so the compiled step
        produces non-finite loss/grads (the guard's job to absorb)."""
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            return x * jnp.nan
        logger.warning(
            "nan_grad fault ignored: integer input batch cannot carry NaN"
        )
        return x

    def _save_mid_epoch(
        self, epoch: int, batches_done: int, loss_sum, metric_sum
    ) -> None:
        """Step-granular checkpoint: epoch ``epoch`` is IN PROGRESS with
        ``batches_done`` batches trained.  The manifest's ``mid_epoch``
        record carries the batch cursor plus the epoch accumulators so
        ``fit(resume=True)`` continues bit-exactly; the end-of-epoch save
        overwrites the same ``checkpoint_<epoch>`` directory.  Costs one
        scalar device sync per save (the accumulator fetch)."""
        hist = self._partial_history()
        hist["mid_epoch"] = {
            "epoch": int(epoch),
            "batches_done": int(batches_done),
            "loss_sum": float(loss_sum),
            "metric_sum": float(metric_sum),
            "skipped_base": int(self._skipped_base),
        }
        from ml_trainer_tpu.telemetry import goodput
        from ml_trainer_tpu.telemetry.spans import span

        ckpt_dir = os.path.join(self.model_dir, "checkpoints")
        if self._sharded_ckpt:
            with span("ckpt_write", epoch=epoch, batch=batches_done,
                      sharded=True), goodput.timed("ckpt_stall"):
                ckpt.save_checkpoint_sharded(
                    ckpt_dir, self.state, hist, epoch, block=False
                )
        elif is_primary():
            # Async: the writer thread serializes this with epoch-end
            # saves (single-queue FIFO), so same-epoch writes never race.
            with span("ckpt_write", epoch=epoch, batch=batches_done,
                      sharded=False), goodput.timed("ckpt_stall"):
                ckpt.save_checkpoint(
                    ckpt_dir, self.state, hist, epoch, block=False
                )

    def _skipped_now(self) -> int:
        """Cumulative on-device skipped-step count (one scalar fetch)."""
        if self.state is None or self.state.skipped_steps is None:
            return 0
        return int(jax.device_get(self.state.skipped_steps))

    def _loss_scale_now(self) -> Optional[float]:
        """Current dynamic loss scale (one scalar fetch; None when
        scaling is off — the gauge/event field then stays absent)."""
        if self.state is None or self.state.loss_scale is None:
            return None
        return float(jax.device_get(self.state.loss_scale))

    def _flight_dir(self) -> str:
        """Flight dumps land next to the checkpoints unless the env var
        redirects them (telemetry/flight.py resolution order)."""
        from ml_trainer_tpu.telemetry.flight import FLIGHT_DIR_ENV

        return os.environ.get(FLIGHT_DIR_ENV) or self.model_dir

    def _write_run_report(self, reason: str) -> None:
        """End-of-run distillation (docs/observability.md run-report
        schema): throughput/MFU, per-host heartbeats, comm bytes by op,
        the resilience ledger, checkpoint write times, straggler/desync
        events.  Primary host, telemetry runs only; never raises (the
        crash path calls this while an exception is in flight)."""
        if not self.telemetry or not is_primary():
            return
        try:
            from ml_trainer_tpu.telemetry.cluster import write_run_report
            from ml_trainer_tpu.telemetry.memory import publish_live_memory

            if self._telemetry is not None:
                # Final goodput decomposition + the live per-device
                # memory view, published so the report's sections read
                # the end-of-run numbers, not the last sync's.
                self._telemetry.goodput.finish()
            publish_live_memory()
            write_run_report(
                self.model_dir,
                history=self.history or self._partial_history(),
                flight=self._flight,
                reason=reason,
            )
        except Exception as e:  # the report documents the run, never ends it
            logger.warning(f"run report write failed: {e}")

    def _maybe_rollback(self, gstep: int = 0) -> bool:
        """Rollback-to-last-good: when ``rollback_bad_steps`` CONSECUTIVE
        steps were skipped as non-finite, restore the newest checkpoint
        that verifies (corrupt ones quarantined) and back the LR off by
        ``rollback_lr_backoff``.  Called at the ``log_every`` sync
        cadence; the check costs one scalar fetch and only runs when
        rollback is enabled."""
        if self.rollback_bad_steps is None or self.state.bad_streak is None:
            return False
        streak = int(jax.device_get(self.state.bad_streak))
        if streak < self.rollback_bad_steps:
            return False
        self._lr_scale *= self.rollback_lr_backoff
        self.rollbacks += 1
        # Crash forensics BEFORE the restore mutates the state: the ring
        # holds the step records leading in, and the rollback event names
        # the bad streak's boundaries (exact when log_every == 1).
        self._flight.record(
            "rollback", step=int(gstep), streak=streak,
            first_bad_step=int(gstep) - streak + 1,
            lr_scale=self._lr_scale,
        )
        if self._telemetry is not None:
            self._telemetry.c_rollbacks.inc()
        self._flight.dump(
            "nan_rollback", out_dir=self._flight_dir(),
            step=int(gstep), first_bad_step=int(gstep) - streak + 1,
            streak=streak,
        )
        zero = jax.device_put(jnp.zeros((), jnp.int32), self._replicated)
        ckpt_dir = os.path.join(self.model_dir, "checkpoints")
        from ml_trainer_tpu.telemetry import goodput

        with goodput.timed("rollback"):
            ckpt.wait_for_checkpoints()  # in-flight async writes must land
            latest = ckpt.latest_valid_checkpoint(
                ckpt_dir, quarantine=is_primary()
            )
            if latest is None:
                # The guard already reverted every bad update, so the live
                # params ARE the last good ones; just clear the streak.
                logger.warning(
                    f"Rollback: {streak} consecutive non-finite steps and "
                    f"no valid checkpoint; LR scale backed off to "
                    f"{self._lr_scale:.4g}, continuing from current params."
                )
                self.state = self.state.replace(bad_streak=zero)
                return True
            skipped_now = self.state.skipped_steps
            if ckpt.checkpoint_format(latest) == 3:
                state, _, _ = ckpt.restore_checkpoint(
                    latest, self.state, self._state_shardings
                )
                self.state = state
            else:
                state, _, _ = ckpt.restore_checkpoint(
                    latest, ckpt.fetch_to_host(self.state)
                )
                from ml_trainer_tpu.parallel import place_tree

                self.state = place_tree(state, self._state_shardings)
            # Keep the cumulative skipped count (diagnostics) but clear
            # the streak — the restored counters predate the event.
            self.state = self.state.replace(
                bad_streak=zero, skipped_steps=skipped_now
            )
            self._reseed_loss_scale()
        logger.warning(
            f"Rollback: {streak} consecutive non-finite steps; restored "
            f"{latest} and backed LR off to scale {self._lr_scale:.4g}."
        )
        return True

    def _write_preempt_marker(self, ckpt_dir: str) -> None:
        """Clean-exit marker: proves the process exited through the
        preemption path (emergency checkpoint committed) rather than
        crashing; ``fit(resume=True)`` logs and consumes it."""
        if not is_primary():
            return
        import json

        os.makedirs(ckpt_dir, exist_ok=True)
        info = dict(self._preempt_info or {})
        info["time"] = time.time()
        # The topology that wrote the emergency checkpoint: a resume at
        # a DIFFERENT shape (elastic restore) knows — and can report —
        # what the world looked like when the preemption landed.
        info["mesh"] = ckpt.state_mesh_topology(self.state)
        tmp = os.path.join(ckpt_dir, "PREEMPTED.json.tmp")
        with open(tmp, "w") as fp:
            json.dump(info, fp)
        os.replace(tmp, os.path.join(ckpt_dir, "PREEMPTED.json"))

    def _consume_preempt_marker(self, ckpt_dir: str) -> None:
        marker = os.path.join(ckpt_dir, "PREEMPTED.json")
        if not os.path.exists(marker):
            return
        import json

        try:
            with open(marker) as fp:
                info = json.load(fp)
        except (OSError, ValueError):
            info = {}
        logger.info(
            f"Clean preemption exit detected ({info}); resuming from the "
            "emergency checkpoint."
        )
        saved_mesh = (info.get("mesh") or {}).get("axes")
        current = ckpt.state_mesh_topology(self.state) if (
            self.state is not None
        ) else None
        if saved_mesh and current and saved_mesh != current.get("axes"):
            logger.info(
                f"Topology changed across the preemption: saved on "
                f"{saved_mesh}, resuming on {current.get('axes')} "
                "(elastic restore reshards the checkpoint)."
            )
        if info.get("time"):
            # Downtime attribution: the age of the marker is the gap the
            # preemption cost between exit and this resume — the
            # goodput ledger's preempt_gap bucket (clamped: clock skew
            # must not mint negative downtime).
            from ml_trainer_tpu.telemetry import goodput

            goodput.account(
                "preempt_gap", max(time.time() - float(info["time"]), 0.0)
            )
        if is_primary():
            try:
                os.remove(marker)
            except OSError:
                pass

    def _reseed_loss_scale(self) -> None:
        """After any restore: a checkpoint written before loss scaling
        existed (or by an fp32 run) lands a zero ``loss_scale`` through
        the compat shim — re-seed it to this run's configured initial
        scale (one scalar fetch; no-op when scaling is off)."""
        if self._loss_scale_cfg is None or self.state.loss_scale is None:
            return
        if float(jax.device_get(self.state.loss_scale)) <= 0.0:
            self.state = self.state.replace(
                loss_scale=jax.device_put(
                    jnp.asarray(
                        self._loss_scale_cfg.init_scale, jnp.float32
                    ),
                    self._replicated,
                ),
                good_steps=jax.device_put(
                    jnp.zeros((), jnp.int32), self._replicated
                ),
            )

    def _sync_skipped_base(self) -> None:
        """Re-anchor the per-epoch skipped-step delta after a restore (one
        scalar fetch; the mid-epoch marker overrides this with the value
        at the interrupted epoch's start)."""
        self._skipped_base = (
            int(jax.device_get(self.state.skipped_steps))
            if self.state.skipped_steps is not None else 0
        )

    def _require_mid_resume_support(self) -> None:
        if self.steps_per_execution > 1:
            raise ValueError(
                "the latest checkpoint is mid-epoch (written by "
                "save_every_steps or a preemption exit), which resumes "
                "through the per-batch dispatch path; restart with "
                "steps_per_execution=1 to continue it"
            )

    def _resume_from_latest(self, ckpt_dir: str) -> int:
        """Restore the latest full checkpoint, multi-host-safely.

        Checkpoints are written by the primary host only (the reference's
        rank-0 save, ref: src/trainer.py:252-254), so on a pod without a
        shared filesystem only host 0 may find one.  Host 0's decision and
        restored state are broadcast to every host so all processes start
        the same epoch with identical replicated state.
        """
        self._consume_preempt_marker(ckpt_dir)
        # Valid-only: corrupt checkpoints (CRC mismatch, missing leaves)
        # are quarantined (*.corrupt) by the primary and the scan falls
        # back to the newest one that verifies.
        latest = ckpt.latest_valid_checkpoint(
            ckpt_dir, quarantine=is_primary()
        )
        multi_host = process_count() > 1
        fmt = ckpt.checkpoint_format(latest) if latest is not None else 0
        epoch_in_name = (
            int(os.path.basename(latest).split("_")[-1].split(".")[0])
            if latest is not None else 0
        )
        if multi_host:
            from jax.experimental import multihost_utils

            # Follow host 0's decision — found, FORMAT and EPOCH — whatever
            # the local disk says: hosts disagreeing on the listing (NFS
            # attribute-cache lag) must still take the SAME branch, or one
            # host enters a broadcast the others never join.
            found, fmt, epoch_in_name = (
                int(v)
                for v in multihost_utils.broadcast_one_to_all(
                    jnp.asarray([
                        1 if latest is not None else 0, fmt, epoch_in_name,
                    ])
                )
            )
            if not found:
                return 1
            if fmt == 3:
                # v3 lives on shared storage: every host reads the epoch
                # host 0 picked (its local listing may lag).
                latest = os.path.join(
                    ckpt_dir, f"{ckpt.CHECKPOINT_PREFIX}{epoch_in_name}"
                )
        elif latest is None:
            return 1
        if fmt == 3:
            # Sharded (v3): every host reads its own shards from the shared
            # checkpoint storage and builds its addressable pieces directly
            # on the target mesh — which may DIFFER from the mesh that
            # saved (elastic resume).  No state broadcast: nothing here is
            # host-0-private, and the full tree never materializes.
            state, saved, done_epoch = ckpt.restore_checkpoint(
                latest, self.state, self._state_shardings
            )
            self.state = state
            self._apply_resume_scalars(saved)
            self._sync_skipped_base()
            self._reseed_loss_scale()
            mid = saved.get("mid_epoch")
            if mid is not None:
                self._require_mid_resume_support()
                self._resume_mid = dict(mid)
                logger.info(
                    f"Resuming mid-epoch {mid['epoch']} at batch "
                    f"{mid['batches_done']} ({latest}, sharded)."
                )
                return int(mid["epoch"])
            logger.info(
                f"Resuming from epoch {done_epoch + 1} ({latest}, sharded)."
            )
            return done_epoch + 1
        if latest is not None:
            state, saved, done_epoch = ckpt.restore_checkpoint(
                latest, ckpt.fetch_to_host(self.state)
            )
        else:  # non-primary host without the file; overwritten by broadcast
            state, saved, done_epoch = ckpt.fetch_to_host(self.state), {}, 0
        plateau = saved.get("plateau", {})
        early = saved.get("early_stop", {})
        mid = saved.get("mid_epoch") or {}
        scalars = np.asarray(
            [
                done_epoch,
                saved.get("lr_scale", 1.0),
                plateau.get("best", np.inf),
                plateau.get("num_bad_epochs", 0),
                plateau.get("scale", 1.0),
                early.get("best_val", np.inf),
                early.get("bad_epochs", 0),
                # Mid-epoch resume cursor (zeros when resuming from an
                # epoch boundary); float32 sums round-trip exactly
                # through float64, so bit-exact resume survives the
                # broadcast.
                1.0 if mid else 0.0,
                mid.get("batches_done", 0),
                mid.get("loss_sum", 0.0),
                mid.get("metric_sum", 0.0),
                mid.get("skipped_base", 0),
            ],
            dtype=np.float64,
        )
        if multi_host:
            from jax.experimental import multihost_utils

            state = multihost_utils.broadcast_one_to_all(state)
            scalars = np.asarray(multihost_utils.broadcast_one_to_all(scalars))
        from ml_trainer_tpu.parallel import place_tree

        self.state = place_tree(state, self._state_shardings)
        # History lists are only written from the primary host, which has
        # them from its local checkpoint (ref: src/trainer.py:252-254).
        self.train_losses = list(saved.get("train_loss", []))
        self.val_losses = list(saved.get("val_loss", []))
        self.train_metrics = list(saved.get("train_metric", []))
        self.val_metrics = list(saved.get("val_metric", []))
        self.skipped_steps = list(saved.get("skipped_steps", []))
        self.rollbacks = int(saved.get("rollbacks", 0))
        self.reshapes = list(saved.get("reshapes", []))
        done_epoch = int(scalars[0])
        self._lr_scale = float(scalars[1])
        if self._plateau is not None:
            self._plateau.best = float(scalars[2])
            self._plateau.num_bad_epochs = int(scalars[3])
            self._plateau.scale = float(scalars[4])
        self._best_val = float(scalars[5])
        self._bad_epochs = int(scalars[6])
        self._sync_skipped_base()
        self._reseed_loss_scale()
        if scalars[7]:
            # Mid-epoch checkpoint: re-enter the manifest's epoch at the
            # saved batch cursor instead of starting the next epoch.
            self._require_mid_resume_support()
            self._resume_mid = {
                "epoch": done_epoch,
                "batches_done": int(scalars[8]),
                "loss_sum": float(scalars[9]),
                "metric_sum": float(scalars[10]),
                "skipped_base": int(scalars[11]),
            }
            logger.info(
                f"Resuming mid-epoch {done_epoch} at batch "
                f"{int(scalars[8])} ({latest})."
            )
            return done_epoch
        start_epoch = done_epoch + 1
        logger.info(f"Resuming from epoch {start_epoch} ({latest}).")
        return start_epoch

    # ------------------------------------------------------------------ test
    def test(self, model=None, test_loader=None):
        """Inference over a loader with the trainer's criterion/metric
        config (ref: src/trainer.py:277-301 — config and weights are
        deliberately decoupled there too).  ``model`` may be a
        ``LoadedModel`` (from ``load_model``), a ``(module, variables)``
        pair, a variables dict for this trainer's module, or None to use the
        trained state."""
        logger.info("Testing..")
        module, variables = self._resolve_model(model)
        # Key by id(module) but keep a strong reference to the module in the
        # entry: a GC'd module's id can be recycled by a new module, which
        # would otherwise silently reuse a stale compiled step.
        key = id(module)
        entry = self._eval_cache.get(key)
        if entry is None or entry[0] is not module:
            takes_train = _module_takes_train(module)
            entry = (
                module,
                self._make_eval_step(
                    module, takes_train, has_bs="batch_stats" in variables
                )[0],
            )
            self._eval_cache[key] = entry
        eval_step = entry[1]
        n = len(test_loader)
        if n == 0:
            raise ValueError("test_loader yields no batches")
        loss_sum = jnp.zeros(())
        metric_sum = jnp.zeros(())
        variables = self._place_eval_variables(variables)
        batches = map(self._place_eval_batch, test_loader)
        with tqdm(batches, total=n, unit="batch") as tepoch:
            for i, (x, y) in enumerate(tepoch):
                loss, metric_val = eval_step(variables, x, y)
                loss_sum = loss_sum + loss
                metric_sum = metric_sum + metric_val
                if (i + 1) % self.log_every == 0 or (i + 1) == n:
                    if self.metric:
                        tepoch.set_postfix(
                            loss=float(loss_sum) / n,
                            metric=self._postfix_metric(metric_sum, i + 1, n),
                        )
                    else:
                        tepoch.set_postfix(loss=float(loss))
        test_loss = float(loss_sum) / n
        if self.metric:
            return test_loss, self._metric_finalize(float(metric_sum) / n)
        return test_loss

    def _place_eval_batch(self, batch):
        """Mesh placement for one eval/predict batch.  User-built loaders
        may have a ragged final batch (drop_last is their choice, ref:
        src/trainer.py:79 keeps all samples); replicate those instead of
        failing to split over the data axis — ONE rule for both APIs."""
        d = self._data_parallel
        sharding = (
            self._batch_sharding
            if d == 1 or batch[0].shape[0] % d == 0
            else self._replicated
        )
        return tuple(
            jax.device_put(a, fit_sharding_to_rank(sharding, np.ndim(a)))
            for a in batch
        )

    def predict(self, loader, model=None, apply_pred_function: bool = True):
        """Model outputs for every batch of ``loader``, in order — the
        inference companion to ``test()`` (which only reports loss/metric;
        the reference's 03-notebook flow has no outputs API at all).

        ``model`` resolves exactly as in ``test()`` (None = the trained
        state).  With ``apply_pred_function`` the trainer's configured
        prediction function (softmax/logsoftmax/None) maps the raw
        logits, matching what the metric engine scores.  Returns one
        stacked numpy array [N, ...].  Loaders may yield (x, y) pairs or
        bare x batches; labels are ignored.  Not available for self-loss
        models (their forward returns a scalar, not outputs)."""
        module, variables = self._resolve_model(model)
        if _module_takes_targets(module):
            raise ValueError(
                "predict() needs model outputs; this model computes its "
                "own loss (clone it with loss_chunk=0 for inference)"
            )
        # Same compiled-program cache as test() (module identity keyed,
        # strong ref against id reuse) so repeat predict() calls do not
        # retrace; apply_pred_function changes the program, so it keys.
        key = (id(module), "predict", bool(apply_pred_function))
        entry = self._eval_cache.get(key)
        if entry is None or entry[0] is not module:
            takes_train = _module_takes_train(module)
            pred_fn = self.pred_function if apply_pred_function else None

            @jax.jit
            def forward(variables, x):
                kwargs = {"train": False} if takes_train else {}
                out = module.apply(variables, x, **kwargs)
                return pred_fn(out) if pred_fn is not None else out

            entry = (module, forward)
            self._eval_cache[key] = entry
        forward = entry[1]

        variables = self._place_eval_variables(variables)
        outs = []
        for batch in loader:
            x = batch[0] if isinstance(batch, (tuple, list)) else batch
            (x,) = self._place_eval_batch((x,))
            outs.append(np.asarray(forward(variables, x)))
        if not outs:
            raise ValueError("loader yields no batches")
        return np.concatenate(outs, axis=0)

    def _place_eval_variables(self, variables):
        """Mesh placement for eval/test variables: leaves already carrying a
        NamedSharding — the trained state, possibly TP/FSDP-partitioned —
        KEEP it (forcing them replicated would all-gather the very params
        the sharding exists to split, and OOM exactly on the models that
        need sharding); only host-loaded leaves (checkpoints arrive as
        numpy) are placed, replicated."""
        def place(leaf):
            if isinstance(
                getattr(leaf, "sharding", None), jax.sharding.NamedSharding
            ):
                return leaf
            return jax.device_put(leaf, self._replicated)

        return jax.tree.map(place, variables)

    def _resolve_model(self, model) -> Tuple[Any, dict]:
        if model is None:
            return self.model, self._state_variables()
        if isinstance(model, LoadedModel):
            return model.module, model.variables
        if isinstance(model, tuple):
            return model
        if isinstance(model, dict):
            variables = model if "params" in model else {"params": model}
            return self.model, variables
        if hasattr(model, "apply"):  # bare flax module: use trainer's state
            return model, self._state_variables()
        raise TypeError(f"Cannot interpret model argument of type {type(model)}")

    # ----------------------------------------------------------- persistence
    def save_model(self, model_dir: str) -> None:
        """Weights-only export every epoch (ref: src/trainer.py:232-235).
        Unlike the reference, saving does NOT move the live model off the
        accelerator (the ref's ``.cpu()`` side effect is a quirk we fix)."""
        logger.info("Saving the model.")
        host_vars = ckpt.fetch_to_host(self._state_variables())
        ckpt.save_model_variables(model_dir, host_vars)
        ckpt.write_model_manifest(model_dir, host_vars)

    def export_lora(self, path: str, name: Optional[str] = None) -> dict:
        """Write the trained adapter as one ``.npz`` artifact — the unit
        the serving engine hot-loads (``Server.load_adapter``, docs/
        serving.md "Batched LoRA adapters"): every ``*_lora_A``/``_B``
        leaf plus a meta record (rank/alpha/targets and the frozen
        base's fingerprint, so a server can flag a base mismatch).
        Requires ``Trainer(lora=...)``.  Returns the meta."""
        if self.lora is None:
            raise ValueError(
                "export_lora requires Trainer(lora=LoraConfig(...))"
            )
        if self.state is None:
            raise ValueError("trainer has no state (datasets were not given)")
        from ml_trainer_tpu.lora import export_lora_artifact

        params = jax.device_get(self.state.params)
        meta = export_lora_artifact(params, self.lora, path, name=name)
        logger.info(
            f"LoRA adapter exported -> {path} "
            f"({meta['n_leaves']} leaves, rank {meta['rank']})."
        )
        return meta

    def export_torch(
        self, path: str, ddp_prefix: bool = False, spatial_inputs=None,
    ) -> str:
        """Write the trained weights as a torch-loadable ``model.pth`` —
        the migration-OUT counterpart of importing reference checkpoints
        (checkpoint/torch_export.py inverts every layout conversion;
        ``ddp_prefix=True`` writes the DDP ``module.``-prefixed key form).
        ``spatial_inputs`` maps layer name -> (C, H, W) for any dense
        layer that consumes a flattened conv output and therefore needs
        the H·W·C -> C·H·W input un-permute (default: MLModel's ``fc1``
        table — pass your own for other conv-to-dense models, or ``{}``
        for models without that boundary).  With ``ema_decay`` set,
        exports the EMA weights — the same public face ``save_model``
        and ``test`` present.

        COLLECTIVE when params are genuinely partitioned across hosts
        (multi-host TP/FSDP): the host fetch is then a global allgather,
        so EVERY process must call this method (mirroring fit()'s
        export guard) — calling it on the primary only would deadlock.
        All hosts fetch; only the primary writes, and secondaries return
        ``path`` without touching the filesystem."""
        from ml_trainer_tpu.parallel.distributed import is_primary, process_count

        variables = self._state_variables()
        export_is_collective = process_count() > 1 and any(
            not leaf.is_fully_addressable
            and not getattr(leaf, "is_fully_replicated", False)
            for leaf in jax.tree.leaves(variables)
        )
        if not is_primary() and not export_is_collective:
            return path  # replicated params: primary-only export
        host_vars = ckpt.fetch_to_host(variables)
        if not is_primary():
            return path  # joined the allgather; the primary writes
        return ckpt.save_torch_checkpoint(
            path, host_vars,
            spatial_inputs=spatial_inputs, ddp_prefix=ddp_prefix,
        )

    def save_history_(self, model_dir: str) -> None:
        """Pickle the history dict (ref: src/trainer.py:237-241) — same
        ``history.pkl`` name so ``load_history`` round-trips — plus a
        ``history.json`` mirror (JSON-safe scalars, including the
        skipped_steps / rollbacks resilience ledger) so offline tooling
        reads a run without unpickling; ``load_history`` prefers it."""
        logger.info("Saving the training history.")
        import json
        import pickle

        os.makedirs(model_dir, exist_ok=True)
        with open(os.path.join(model_dir, "history.pkl"), "wb") as fp:
            pickle.dump(self.history, fp)
        tmp = os.path.join(model_dir, "history.json.tmp")
        with open(tmp, "w", encoding="utf-8") as fp:
            # numpy scalars riding in the lists coerce through float().
            json.dump(self.history, fp, default=float, indent=1)
        os.replace(tmp, os.path.join(model_dir, "history.json"))

    def clear(self) -> None:
        """GC pass (ref: src/trainer.py:303-305).  XLA's arena allocator has
        no ``empty_cache`` analog to call — nothing to release."""
        gc.collect()

    def validate_kwargs(self, kwargs, allowed_kwargs,
                        error_message="Keyword argument not understood:"):
        """Parity shim (ref: src/trainer.py:307-311)."""
        validate_kwargs(kwargs, allowed_kwargs, error_message)
