"""TrainState — the complete, functional training state pytree.

The reference's training state is implicit object state scattered across the
torch model, optimizer and scheduler (ref: src/trainer.py:96-113).  On TPU
the whole state must be a single pytree so one ``jax.jit`` step can donate
and update it in place on-device; it also makes full checkpoint/resume (a
reference gap, SURVEY.md §5) trivial: serialize the pytree.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import struct


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray  # global step counter (drives the LR schedule)
    params: Any
    opt_state: Any
    batch_stats: Any  # {} for models without BatchNorm
    rng: jnp.ndarray  # functional PRNG key (the torch.manual_seed analog,
    #                   ref: src/trainer.py:47, but split per step)
    ema_params: Any = None  # EMA of params when Trainer(ema_decay=...) is
    #                         set; None (an empty pytree) otherwise, so
    #                         checkpoints without EMA keep the same leaves
    # Nonfinite-guard counters (int32 scalars, maintained ON-DEVICE by the
    # compiled train step so guarding adds no host sync): cumulative count
    # of steps skipped for non-finite loss/grads, and the current streak
    # of consecutive skipped steps (drives rollback).  None for states
    # built outside the Trainer; checkpoints written before these fields
    # existed restore through the compat shim (checkpoint.py).
    skipped_steps: Any = None
    bad_streak: Any = None
    # Mixed-precision dynamic loss scaling (precision.py): the current
    # scale (float32 scalar) and the consecutive-finite-step counter that
    # drives scale growth.  Maintained ON-DEVICE by the compiled step,
    # like the guard counters; None whenever loss scaling is off (the
    # fp32 default keeps the exact pre-policy pytree, so fp32 checkpoints
    # and trajectories are unchanged).
    loss_scale: Any = None
    good_steps: Any = None
