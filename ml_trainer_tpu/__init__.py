"""ml_trainer_tpu — a TPU-native training framework (JAX / XLA / pjit / Pallas).

Brand-new implementation with the capabilities of the reference trainer
(abbomarengo/ml-trainer): a config-driven ``Trainer`` with pluggable
optimizers, LR schedules, losses, metrics and prediction functions
(ref: src/trainer.py:22-311), a ``Loader`` data abstraction
(ref: src/dataloader.py:5), the LeNet-style ``MLModel``
(ref: src/model.py:7-24) plus a TPU model zoo, and history/checkpoint
utilities (ref: src/utils/utils.py:9-68) — all built mesh-first:

* the train step is a single compiled XLA program (``jax.jit`` under a
  ``jax.sharding.Mesh``) whose gradient all-reduce is a ``psum`` over the
  ICI/DCN mesh — the TPU-native equivalent of the reference's
  DistributedDataParallel + SMDDP stack (ref: src/trainer.py:98, 43-44);
* the input pipeline shards the global batch across hosts and
  double-buffers device transfers (the DistributedSampler + DataLoader
  analog, ref: src/trainer.py:60-64, 77-79);
* checkpointing saves full training state (params, optimizer state, step,
  PRNG key) and supports resume — a deliberate extension over the
  reference's save-only weights path (ref: src/trainer.py:232-235).
"""

from ml_trainer_tpu.config import TrainerConfig, validate_kwargs
from ml_trainer_tpu.trainer import Trainer
from ml_trainer_tpu.data import Loader, ArrayDataset, ShardedSampler
from ml_trainer_tpu.models import MLModel
from ml_trainer_tpu.utils.utils import load_history, load_model, plot_history
from ml_trainer_tpu.generate import beam_search, generate, generate_ragged
from ml_trainer_tpu.lora import LoraConfig
from ml_trainer_tpu.speculative import (
    DraftModelDrafter,
    NgramDrafter,
    speculative_generate,
)

__version__ = "0.4.0"  # kept in lockstep with pyproject.toml (test-pinned)

__all__ = [
    "Trainer",
    "TrainerConfig",
    "validate_kwargs",
    "Loader",
    "ArrayDataset",
    "ShardedSampler",
    "MLModel",
    "load_history",
    "load_model",
    "plot_history",
    "generate",
    "generate_ragged",
    "beam_search",
    "speculative_generate",
    "LoraConfig",
    "NgramDrafter",
    "DraftModelDrafter",
    "__version__",
]
