"""Mixed-precision policy: bf16 compute against fp32 master params.

The reference trains everything in fp32 (torch default); on TPU the MXU's
bf16 throughput is ~2x fp32, so the standard recipe (arXiv 1710.03740,
every production TPU trainer since) is a *policy* of three dtypes:

* ``compute`` — forward/backward activations and matmuls (bf16);
* ``params``  — the master copy the optimizer updates (fp32, lives in
  ``TrainState``; cast to ``compute`` once per step at the top of the
  loss function, so gradients come back fp32 through the cast's vjp);
* ``output``  — logits/loss as consumed by the criterion and metrics
  (fp32: a softmax cross-entropy over bf16 logits loses ulp exactly
  where the loss signal lives).

bf16 keeps fp32's 8-bit exponent, so unlike fp16 it rarely *needs* loss
scaling — but small gradients still flush to zero in bf16 backward
accumulation, and the policy composes with the trainer's existing
non-finite guard, so :class:`LossScaleConfig` implements the standard
dynamic scheme anyway (scale the loss up, unscale the grads, halve on
overflow WITHOUT burning a rollback streak, grow back after a streak of
healthy steps).  ``Trainer(precision='bf16')`` turns the whole stack on;
``Trainer(precision='bf16', loss_scale=None)`` keeps bare bf16.

Threading (the three layers the policy touches):

* ``models/registry.py`` — ``get_model(name, precision=...)`` maps the
  policy's compute dtype onto the module's ``dtype`` knob for the
  families that carry one (the transformer zoo), so module-internal
  casts agree with the trainer's;
* ``train_state.py`` — ``loss_scale`` / ``good_steps`` ride in the
  state so the compiled step maintains them with no host sync;
* ``trainer.py`` — casts params/batch to ``compute`` inside the loss
  function, the criterion back at ``output``, and folds the
  scale-backoff/growth arithmetic into the non-finite guard.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Union

import jax
import jax.numpy as jnp

# Dynamic loss-scaling defaults (the torch.cuda.amp / t5x constants,
# adjusted for bf16's wide exponent: a smaller initial scale converges to
# steady state faster and overflow is rare anyway).
DEFAULT_INIT_SCALE = 2.0 ** 15
MIN_SCALE = 1.0
MAX_SCALE = 2.0 ** 24
GROWTH_FACTOR = 2.0
BACKOFF_FACTOR = 0.5
GROWTH_INTERVAL = 2000  # consecutive finite steps before the scale doubles


@dataclasses.dataclass(frozen=True)
class Precision:
    """The (compute, params, output) dtype triple of one training run."""

    compute: Any = jnp.float32
    params: Any = jnp.float32
    output: Any = jnp.float32

    @property
    def active(self) -> bool:
        """True when compute differs from the fp32 master dtype — the only
        case the trainer's cast machinery engages (fp32 stays the exact
        pre-policy program, bit-identical)."""
        return jnp.dtype(self.compute) != jnp.dtype(self.params)

    def label(self) -> str:
        return jnp.dtype(self.compute).name


# Named policies — the strings Trainer(precision=...) and
# get_model(precision=...) accept.
POLICIES = {
    "fp32": Precision(),
    "float32": Precision(),
    "bf16": Precision(compute=jnp.bfloat16),
    "bfloat16": Precision(compute=jnp.bfloat16),
    "mixed_bf16": Precision(compute=jnp.bfloat16),
}


def resolve_precision(policy: Union[str, Precision, None]) -> Precision:
    """Resolve a policy name / Precision / None to a Precision instance.
    fp32 params are a hard invariant here (the master copy IS the
    TrainState; a non-fp32 master would silently change every checkpoint
    and resume path), so only compute/output vary."""
    if policy is None:
        return POLICIES["fp32"]
    if isinstance(policy, Precision):
        if jnp.dtype(policy.params) != jnp.dtype(jnp.float32):
            raise ValueError(
                "Precision.params must be float32 (the TrainState master "
                f"copy); got {jnp.dtype(policy.params).name}"
            )
        return policy
    try:
        return POLICIES[str(policy).lower()]
    except KeyError:
        raise ValueError(
            f"unknown precision policy {policy!r}; expected one of "
            f"{sorted(set(POLICIES))} or a Precision instance"
        ) from None


@dataclasses.dataclass(frozen=True)
class LossScaleConfig:
    """Dynamic loss-scaling knobs (all static: they compile into the step)."""

    init_scale: float = DEFAULT_INIT_SCALE
    growth_factor: float = GROWTH_FACTOR
    backoff_factor: float = BACKOFF_FACTOR
    growth_interval: int = GROWTH_INTERVAL
    min_scale: float = MIN_SCALE
    max_scale: float = MAX_SCALE


def resolve_loss_scale(
    loss_scale: Union[str, float, LossScaleConfig, None],
    precision: Precision,
) -> Optional[LossScaleConfig]:
    """Normalize the Trainer's ``loss_scale`` knob.

    ``'dynamic'`` (the default) -> the standard dynamic config; a float ->
    a STATIC scale (growth/backoff disabled by pinning min == max == init);
    ``None`` -> no scaling.  Inactive (fp32) precision always resolves to
    None — the scale arithmetic must not enter the fp32 program."""
    if not precision.active or loss_scale is None:
        return None
    if isinstance(loss_scale, LossScaleConfig):
        return loss_scale
    if isinstance(loss_scale, str):
        if loss_scale.lower() != "dynamic":
            raise ValueError(
                f"loss_scale must be 'dynamic', a positive number, a "
                f"LossScaleConfig, or None; got {loss_scale!r}"
            )
        return LossScaleConfig()
    scale = float(loss_scale)
    if scale <= 0:
        raise ValueError(f"loss_scale must be positive, got {scale}")
    return LossScaleConfig(
        init_scale=scale, min_scale=scale, max_scale=scale,
        growth_factor=1.0, backoff_factor=1.0,
    )


def cast_floating(tree, dtype):
    """Cast every inexact leaf of ``tree`` to ``dtype`` (integer leaves —
    token ids, masks — pass through untouched)."""
    def cast(leaf):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.inexact):
            return leaf.astype(dtype)
        return leaf

    return jax.tree.map(cast, tree)


def cast_like(tree, ref):
    """Cast each leaf of ``tree`` back to the dtype of the matching leaf in
    ``ref`` — restores state-dtype invariants (batch_stats mutated in bf16
    must come home fp32 or checkpoints/where-selects break)."""
    return jax.tree.map(
        lambda leaf, r: leaf.astype(r.dtype)
        if hasattr(r, "dtype") and hasattr(leaf, "astype") else leaf,
        tree, ref,
    )
