"""Export flax parameter trees back to reference-style torch checkpoints.

The inverse of ``torch_import``: a model trained here can hand its
weights back to the reference stack (or any torch consumer) as the
``model.pth`` ``state_dict`` the reference's ``load_model`` reads
(ref: src/utils/utils.py:15-28) — migration runs in BOTH directions.

Layout conversions mirror the import exactly:

* conv kernels: flax HWIO -> torch OIHW;
* dense kernels: flax (in, out) -> torch (out, in);
* the first dense after a conv stack un-permutes its input features from
  this framework's H·W·C flatten order back to torch's C·H·W
  (``spatial_inputs``, same table as the import — MLModel's ``fc1``);
* BatchNorm ``scale``/``mean``/``var`` -> ``weight``/``running_mean``/
  ``running_var``.

Round-trip identity (export then import == original tree) is test-pinned
(tests/test_checkpoint.py).
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional, Tuple

import numpy as np

from ml_trainer_tpu.checkpoint.torch_import import MLMODEL_SPATIAL_INPUTS


def convert_to_torch_state_dict(
    params: Mapping[str, Mapping[str, np.ndarray]],
    spatial_inputs: Optional[Dict[str, Tuple[int, int, int]]] = None,
    ddp_prefix: bool = False,
) -> Dict[str, np.ndarray]:
    """flax ``{layer: {kernel/bias/...}}`` -> torch ``{layer.weight: ...}``.

    ``ddp_prefix=True`` writes ``module.``-prefixed keys — the form a
    DDP-trained reference checkpoint carries (its ``load_model`` strips
    them, so either form loads there)."""
    spatial_inputs = (
        MLMODEL_SPATIAL_INPUTS if spatial_inputs is None else spatial_inputs
    )
    out: Dict[str, np.ndarray] = {}
    prefix = "module." if ddp_prefix else ""

    def put(layer: str, leaf: str, arr: np.ndarray) -> None:
        out[f"{prefix}{layer.replace('/', '.')}.{leaf}"] = arr

    for layer, leaves in params.items():
        for leaf, value in leaves.items():
            if isinstance(value, Mapping):
                raise ValueError(
                    f"nested module {layer}/{leaf}: flatten the tree to "
                    "{layer: {leaf: array}} first (transformer trees need "
                    "a model-specific key mapping, not this generic one)"
                )
            arr = np.asarray(value)
            if leaf == "kernel":
                if arr.ndim == 4:  # HWIO -> OIHW
                    put(layer, "weight", arr.transpose(3, 2, 0, 1))
                elif arr.ndim == 2:
                    w = arr.T  # (in, out) -> (out, in)
                    if layer in spatial_inputs:
                        c, h, w_ = spatial_inputs[layer]
                        # Columns are H*W*C-ordered here; torch flattens
                        # C*H*W — permute back.
                        w = (
                            w.reshape(w.shape[0], h, w_, c)
                            .transpose(0, 3, 1, 2)
                            .reshape(w.shape[0], c * h * w_)
                        )
                    put(layer, "weight", w)
                else:
                    # A silent pass-through would write a wrong-layout
                    # tensor torch loads without error (and a 1-D kernel
                    # would import back as 'scale', breaking the
                    # round-trip identity) — refuse loudly instead.
                    raise ValueError(
                        f"{layer}/kernel has rank {arr.ndim}; only dense "
                        "(2-D) and conv (4-D HWIO) kernels have a defined "
                        "torch export layout"
                    )
            elif leaf == "scale":
                put(layer, "weight", arr)
            elif leaf == "mean":
                put(layer, "running_mean", arr)
            elif leaf == "var":
                put(layer, "running_var", arr)
            else:
                put(layer, leaf, arr)
    return out


def save_torch_checkpoint(
    path: str,
    variables: Mapping,
    spatial_inputs: Optional[Dict[str, Tuple[int, int, int]]] = None,
    ddp_prefix: bool = False,
) -> str:
    """Write a torch-loadable ``model.pth`` from flax ``variables`` (the
    ``{'params': ...}`` dict or a bare params tree).  BatchNorm batch
    stats merge in from ``variables['batch_stats']`` when present."""
    import torch

    params = dict(variables.get("params", variables))
    batch_stats = variables.get("batch_stats")
    if batch_stats:
        merged: Dict[str, Dict] = {
            k: dict(v) for k, v in params.items()
        }
        for layer, stats in batch_stats.items():
            merged.setdefault(layer, {}).update(stats)
        params = merged
    state = convert_to_torch_state_dict(params, spatial_inputs, ddp_prefix)
    # np.array (writable copy): torch.from_numpy warns on the read-only
    # views np.asarray produces from jax arrays.
    torch.save(
        {k: torch.from_numpy(np.array(v)) for k, v in state.items()}, path
    )
    return path
