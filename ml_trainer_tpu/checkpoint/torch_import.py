"""Import reference torch checkpoints into flax parameter trees.

Replicates the compatibility behaviour of the reference's ``load_model``
(ref: src/utils/utils.py:15-28): DDP-saved state dicts carry a ``module.``
prefix which is stripped, falling back to a direct load.  On top of that,
layouts are converted for the TPU-native models:

* conv weights: torch OIHW -> flax HWIO;
* linear weights: torch (out, in) -> flax (in, out);
* the first dense layer after a conv stack additionally permutes its input
  features from torch's C·H·W flatten order to this framework's H·W·C order
  (``spatial_inputs`` maps layer name -> (C, H, W); MLModel's ``fc1`` is
  (16, 5, 5), ref: src/model.py:11, 20).
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional, Tuple

import numpy as np

# MLModel's fc1 consumes the flattened 16x5x5 conv output (ref: src/model.py:11).
MLMODEL_SPATIAL_INPUTS = {"fc1": (16, 5, 5)}


def _strip_ddp_prefix(state_dict: Mapping[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Remove the DDP ``module.`` prefix when every key carries it
    (ref: src/utils/utils.py:17-27's try/except, made explicit)."""
    keys = list(state_dict)
    if keys and all(k.startswith("module.") for k in keys):
        return {k[len("module."):]: v for k, v in state_dict.items()}
    return dict(state_dict)


def convert_torch_state_dict(
    state_dict: Mapping[str, np.ndarray],
    spatial_inputs: Optional[Dict[str, Tuple[int, int, int]]] = None,
) -> Dict[str, Dict[str, np.ndarray]]:
    """torch ``{layer.weight/bias: tensor}`` -> flax ``{layer: {kernel/bias}}``."""
    spatial_inputs = (
        MLMODEL_SPATIAL_INPUTS if spatial_inputs is None else spatial_inputs
    )
    state_dict = _strip_ddp_prefix(state_dict)
    params: Dict[str, Dict[str, np.ndarray]] = {}
    for key, value in state_dict.items():
        arr = np.asarray(value)
        layer, _, leaf = key.rpartition(".")
        layer = layer.replace(".", "/")
        entry = params.setdefault(layer, {})
        if leaf == "weight":
            if arr.ndim == 4:  # OIHW -> HWIO
                entry["kernel"] = arr.transpose(2, 3, 1, 0)
            elif arr.ndim == 2:
                if layer in spatial_inputs:
                    c, h, w = spatial_inputs[layer]
                    arr = (
                        arr.reshape(arr.shape[0], c, h, w)
                        .transpose(0, 2, 3, 1)
                        .reshape(arr.shape[0], c * h * w)
                    )
                entry["kernel"] = arr.T
            else:
                entry["scale" if arr.ndim == 1 else "kernel"] = arr
        elif leaf == "bias":
            entry["bias"] = arr
        elif leaf in ("running_mean", "running_var"):
            entry["mean" if leaf == "running_mean" else "var"] = arr
        elif leaf == "num_batches_tracked":
            continue
        else:
            entry[leaf] = arr
    return params


def load_torch_checkpoint(
    path: str,
    spatial_inputs: Optional[Dict[str, Tuple[int, int, int]]] = None,
) -> Dict[str, Dict[str, np.ndarray]]:
    """Load a reference ``model.pth`` into flax params (torch-cpu only)."""
    import torch

    state_dict = torch.load(path, map_location="cpu", weights_only=True)
    state_dict = {k: v.numpy() for k, v in state_dict.items()}
    return convert_torch_state_dict(state_dict, spatial_inputs)
