"""Checkpoint I/O: per-epoch model export + full training-state save/resume.

The reference saves only model weights every epoch on rank 0 and pickles the
history once at the end (ref: src/trainer.py:232-241, 252-256); ``fit()``
cannot resume.  Here both layers exist:

* ``save_model_variables`` / ``load_model_variables`` — weights-only export
  (``model.msgpack``, the ``model.pth`` analog) for inference and the
  03-notebook flow.
* ``save_checkpoint`` / ``restore_checkpoint`` — the full TrainState
  (params, optimizer state, step, PRNG key, batch_stats) plus history, so a
  preempted TPU job resumes exactly — the deliberate extension called out in
  SURVEY.md §5.

Writes are atomic (tmp + rename) and host-0-only at the call sites, matching
the reference's rank-0 gate (ref: src/trainer.py:252-254).
"""

from __future__ import annotations

import os
import pickle
import re
from typing import Any, Optional, Tuple

import jax
from flax import serialization

MODEL_FILE = "model.msgpack"
CHECKPOINT_PREFIX = "checkpoint_"
_CKPT_RE = re.compile(rf"^{CHECKPOINT_PREFIX}(\d+)\.pkl$")


def _atomic_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as fp:
        fp.write(data)
    os.replace(tmp, path)


def save_model_variables(model_dir: str, variables: Any) -> str:
    """Weights-only export, every-epoch cadence (ref: src/trainer.py:232-235)."""
    os.makedirs(model_dir, exist_ok=True)
    path = os.path.join(model_dir, MODEL_FILE)
    _atomic_write(path, serialization.to_bytes(jax.device_get(variables)))
    return path


def load_model_variables(path: str) -> Any:
    """Template-free restore of a ``model.msgpack`` into nested dicts."""
    if os.path.isdir(path):
        path = os.path.join(path, MODEL_FILE)
    with open(path, "rb") as fp:
        return serialization.msgpack_restore(fp.read())


def save_checkpoint(
    ckpt_dir: str,
    state: Any,
    history: dict,
    epoch: int,
    keep: int = 3,
) -> str:
    os.makedirs(ckpt_dir, exist_ok=True)
    payload = {
        "state": serialization.to_state_dict(jax.device_get(state)),
        "history": history,
        "epoch": epoch,
    }
    path = os.path.join(ckpt_dir, f"{CHECKPOINT_PREFIX}{epoch}.pkl")
    _atomic_write(path, pickle.dumps(payload))
    prune_checkpoints(ckpt_dir, keep)
    return path


def _scan_checkpoints(ckpt_dir: str):
    """Sorted (epoch, filename) pairs of checkpoints in a directory."""
    if not os.path.isdir(ckpt_dir):
        return []
    found = []
    for name in os.listdir(ckpt_dir):
        m = _CKPT_RE.match(name)
        if m:
            found.append((int(m.group(1)), name))
    return sorted(found)


def prune_checkpoints(ckpt_dir: str, keep: int) -> None:
    if not keep:
        return
    for _, name in _scan_checkpoints(ckpt_dir)[:-keep]:
        os.remove(os.path.join(ckpt_dir, name))


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    found = _scan_checkpoints(ckpt_dir)
    if not found:
        return None
    return os.path.join(ckpt_dir, found[-1][1])


def restore_checkpoint(path: str, state_template: Any) -> Tuple[Any, dict, int]:
    """Restore (state, history, epoch); the template supplies pytree
    structure (the trainer always has one before restoring)."""
    with open(path, "rb") as fp:
        payload = pickle.load(fp)
    state = serialization.from_state_dict(state_template, payload["state"])
    return state, payload["history"], payload["epoch"]
