"""Checkpoint I/O: per-epoch model export + full training-state save/resume.

The reference saves only model weights every epoch on rank 0 and pickles the
history once at the end (ref: src/trainer.py:232-241, 252-256); ``fit()``
cannot resume.  Here both layers exist:

* ``save_model_variables`` / ``load_model_variables`` — weights-only export
  (``model.msgpack``, the ``model.pth`` analog) for inference and the
  03-notebook flow.
* ``save_checkpoint`` / ``restore_checkpoint`` — the full TrainState
  (params, optimizer state, step, PRNG key, batch_stats) plus history, so a
  preempted TPU job resumes exactly — the deliberate extension called out in
  SURVEY.md §5.

Checkpoint format (v2): a ``checkpoint_<epoch>/`` DIRECTORY holding one
``.npy`` file per state leaf plus a JSON manifest — per-leaf, streamed
writes that scale to GPT-2-class states (the v1 monolithic pickle
double-buffered ~1.5GB in RAM and executed arbitrary bytes on load;
``.npy`` restores with ``allow_pickle=False``).  Writes are atomic
(tmp dir + rename), host-0-only at the call sites matching the reference's
rank-0 gate (ref: src/trainer.py:252-254), and optionally asynchronous:
``save_checkpoint(..., block=False)`` snapshots device→host synchronously
(the compiled step donates state buffers, so references alone would go
stale) and hands the disk writes to a single background writer thread so
the training loop isn't stalled by I/O.  Legacy v1 ``.pkl`` checkpoints
remain readable.
"""

from __future__ import annotations

import copy
import json
import os
import pickle
import re
import shutil
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, List, Optional, Tuple

import jax
import numpy as np
from flax import serialization

MODEL_FILE = "model.msgpack"
CHECKPOINT_PREFIX = "checkpoint_"
MANIFEST = "manifest.json"
_CKPT_RE = re.compile(rf"^{CHECKPOINT_PREFIX}(\d+)(\.pkl)?$")

# One writer thread: checkpoint writes are ordered (epoch N lands before
# N+1) and never overlap, while the training loop keeps running.
_writer = ThreadPoolExecutor(max_workers=1, thread_name_prefix="ckpt-writer")
_pending: List[Future] = []
_pending_lock = threading.Lock()


def _atomic_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as fp:
        fp.write(data)
    os.replace(tmp, path)


def save_model_variables(model_dir: str, variables: Any) -> str:
    """Weights-only export, every-epoch cadence (ref: src/trainer.py:232-235)."""
    return write_model_bytes(
        model_dir, serialization.to_bytes(fetch_to_host(variables))
    )


def write_model_bytes(model_dir: str, data: bytes) -> str:
    """Write an already-serialized export — lets a caller exporting to two
    places (every-epoch + best) fetch and serialize once."""
    os.makedirs(model_dir, exist_ok=True)
    path = os.path.join(model_dir, MODEL_FILE)
    _atomic_write(path, data)
    return path


def load_model_variables(path: str) -> Any:
    """Template-free restore of a ``model.msgpack`` into nested dicts."""
    if os.path.isdir(path):
        path = os.path.join(path, MODEL_FILE)
    with open(path, "rb") as fp:
        return serialization.msgpack_restore(fp.read())


# ----------------------------------------------------------- v2 leaf format
def _flatten(tree: Any, path=()):
    """(path tuple, leaf) pairs over the nested state dict, sorted keys.
    Empty dicts (optax EmptyState, empty batch_stats) are themselves leaves —
    dropping them would change the state-dict structure on restore."""
    if isinstance(tree, dict) and tree:
        for key in sorted(tree):
            yield from _flatten(tree[key], path + (str(key),))
    else:
        yield path, tree


def _unflatten(pairs) -> Any:
    root: dict = {}
    for path, leaf in pairs:
        node = root
        for key in path[:-1]:
            node = node.setdefault(key, {})
        node[path[-1]] = leaf
    return root


def _write_checkpoint_dir(
    final_dir: str, state_dict: Any, history: dict, epoch: int
) -> None:
    tmp_dir = final_dir + ".tmp"
    if os.path.isdir(tmp_dir):
        shutil.rmtree(tmp_dir)
    os.makedirs(tmp_dir)
    leaves = []
    for i, (path, leaf) in enumerate(_flatten(state_dict)):
        if isinstance(leaf, dict):  # empty container leaf
            leaves.append({"path": list(path), "empty": True})
            continue
        if leaf is None:  # e.g. TrainState.ema_params with EMA disabled
            leaves.append({"path": list(path), "none": True})
            continue
        arr = np.asarray(jax.device_get(leaf))
        fname = f"leaf_{i:05d}.npy"
        np.save(os.path.join(tmp_dir, fname), arr, allow_pickle=False)
        leaves.append({"path": list(path), "file": fname})
    manifest = {
        "format": 2,
        "epoch": epoch,
        "history": history,
        "leaves": leaves,
    }
    with open(os.path.join(tmp_dir, MANIFEST), "w") as fp:
        json.dump(manifest, fp)
    if os.path.isdir(final_dir):
        shutil.rmtree(final_dir)
    os.replace(tmp_dir, final_dir)


def wait_for_checkpoints() -> None:
    """Join all in-flight async checkpoint writes, re-raising any failure."""
    with _pending_lock:
        pending, _pending[:] = list(_pending), []
    for fut in pending:
        fut.result()


def fetch_to_host(tree: Any) -> Any:
    """Device→host snapshot that survives host-spanning shardings.

    ``jax.device_get`` raises on arrays that are not fully addressable
    (e.g. ZeRO-1 optimizer moments sharded over a multi-host ``data``
    axis); those leaves are gathered across processes first.  Single-host
    arrays take the plain fast path."""
    def fetch(leaf):
        if hasattr(leaf, "is_fully_addressable") and not leaf.is_fully_addressable:
            from jax.experimental import multihost_utils

            return np.asarray(
                multihost_utils.process_allgather(leaf, tiled=True)
            )
        return jax.device_get(leaf)

    return jax.tree.map(fetch, tree)


def save_checkpoint(
    ckpt_dir: str,
    state: Any,
    history: dict,
    epoch: int,
    keep: int = 3,
    block: bool = True,
) -> str:
    """Write ``checkpoint_<epoch>/``.  With ``block=False`` the device→host
    snapshot happens synchronously (the training step may DONATE the state
    buffers, so the device arrays can be invalid by the next step) and only
    the disk writes run on the background writer thread; call
    ``wait_for_checkpoints()`` (the trainer does at fit-end) to surface
    errors."""
    os.makedirs(ckpt_dir, exist_ok=True)
    state_dict = fetch_to_host(serialization.to_state_dict(state))
    # Deep-copy on the caller's thread: the trainer hands us its LIVE
    # history lists, which the next epoch mutates while the writer runs.
    history = copy.deepcopy(history)
    path = os.path.join(ckpt_dir, f"{CHECKPOINT_PREFIX}{epoch}")

    def job():
        _write_checkpoint_dir(path, state_dict, history, epoch)
        prune_checkpoints(ckpt_dir, keep)

    if block:
        job()
    else:
        fut = _writer.submit(job)
        with _pending_lock:
            _pending.append(fut)
    return path


def _scan_checkpoints(ckpt_dir: str):
    """Sorted (epoch, filename) pairs of checkpoints (v2 dirs + v1 pkls).
    In-flight ``.tmp`` dirs are skipped."""
    if not os.path.isdir(ckpt_dir):
        return []
    found = []
    for name in os.listdir(ckpt_dir):
        m = _CKPT_RE.match(name)
        if m:
            found.append((int(m.group(1)), name))
    return sorted(found)


def prune_checkpoints(ckpt_dir: str, keep: int) -> None:
    if not keep:
        return
    for _, name in _scan_checkpoints(ckpt_dir)[:-keep]:
        full = os.path.join(ckpt_dir, name)
        if os.path.isdir(full):
            shutil.rmtree(full)
        else:
            os.remove(full)


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    found = _scan_checkpoints(ckpt_dir)
    if not found:
        return None
    return os.path.join(ckpt_dir, found[-1][1])


def _reconcile_ema(state_template: Any, saved: Any) -> Any:
    """Make checkpoints portable across the ``ema_decay`` setting (and
    across its addition to TrainState).  Missing/None EMA + EMA-enabled
    template → seed the EMA from the saved params; EMA in the checkpoint +
    EMA-disabled template → drop it; pre-EMA checkpoints → inject None."""
    if not isinstance(saved, dict):
        return saved
    tpl = serialization.to_state_dict(state_template)
    if not (isinstance(tpl, dict) and "ema_params" in tpl):
        return saved
    want_ema = tpl["ema_params"] is not None
    have = saved.get("ema_params")
    if want_ema and have is None:
        if "params" not in saved:
            raise ValueError(
                "Cannot seed EMA from checkpoint: it has no 'params' entry "
                f"(found keys {sorted(saved)}) — the checkpoint is malformed."
            )
        # EMA turned on for (or added to) this run: start it at the
        # restored params, exactly how a fresh Trainer seeds it.  Aliasing
        # the host arrays is fine — restore only reads them, and
        # device_put gives each leaf its own device buffer.
        saved = dict(saved)
        saved["ema_params"] = saved["params"]
    elif not want_ema:
        saved = dict(saved)
        saved["ema_params"] = None
    return saved


def _inject_masked_levels(template_sd: Any, saved: Any) -> Any:
    """Align a pre-decay-mask opt_state dict with a template that carries
    ``optax.masked`` wrappers: wherever the template expects the
    single-key ``{'inner_state': ...}`` level (MaskedState) and the saved
    dict holds the bare inner state, inject the level.  Purely structural
    — leaf values are untouched."""
    if isinstance(template_sd, dict):
        t_keys = set(template_sd.keys())
        saved_is_masked = isinstance(saved, dict) and set(
            saved.keys()
        ) == {"inner_state"}
        if t_keys == {"inner_state"} and not saved_is_masked:
            return {
                "inner_state": _inject_masked_levels(
                    template_sd["inner_state"], saved
                )
            }
        if isinstance(saved, dict):
            return {
                k: (
                    _inject_masked_levels(template_sd[k], v)
                    if k in template_sd else v
                )
                for k, v in saved.items()
            }
    return saved


def _from_state_dict_compat(state_template: Any, saved: Any) -> Any:
    """``from_state_dict`` with fallbacks for checkpoints written by older
    trainer versions: (a) before every optimizer was wrapped in
    ``chain(clip-or-identity, inner)`` — re-nest under the template's
    ``{'0': {}, '1': inner}`` shape; (b) before a weight-decay mask was
    always passed — inject the ``MaskedState`` ``inner_state`` levels the
    new opt_state carries.  Retried in combination; on failure the
    ORIGINAL mismatch is re-raised (e.g. optimizer changed between save
    and resume — the real story, not a fallback's secondary failure)."""
    saved = _reconcile_ema(state_template, saved)
    try:
        return serialization.from_state_dict(state_template, saved)
    except (ValueError, KeyError, AttributeError) as orig:
        if not (isinstance(saved, dict) and "opt_state" in saved):
            raise
        template_sd = serialization.to_state_dict(state_template)
        candidates = []
        renested = {"0": {}, "1": saved["opt_state"]}
        for opt_sd in (saved["opt_state"], renested):
            candidates.append(opt_sd)
            candidates.append(
                _inject_masked_levels(template_sd.get("opt_state"), opt_sd)
            )
        for opt_sd in candidates[1:]:  # [0] is what already failed
            wrapped = dict(saved)
            wrapped["opt_state"] = opt_sd
            try:
                return serialization.from_state_dict(state_template, wrapped)
            except Exception:
                continue
        raise orig


def restore_checkpoint(path: str, state_template: Any) -> Tuple[Any, dict, int]:
    """Restore (state, history, epoch); the template supplies pytree
    structure (the trainer always has one before restoring)."""
    if os.path.isdir(path):
        with open(os.path.join(path, MANIFEST)) as fp:
            manifest = json.load(fp)
        pairs = [
            (
                tuple(leaf["path"]),
                {}
                if leaf.get("empty")
                else None
                if leaf.get("none")
                else np.load(
                    os.path.join(path, leaf["file"]), allow_pickle=False
                ),
            )
            for leaf in manifest["leaves"]
        ]
        state = _from_state_dict_compat(state_template, _unflatten(pairs))
        return state, manifest["history"], manifest["epoch"]
    # Legacy v1 monolithic pickle (round-1 checkpoints).
    with open(path, "rb") as fp:
        payload = pickle.load(fp)
    state = _from_state_dict_compat(state_template, payload["state"])
    return state, payload["history"], payload["epoch"]
