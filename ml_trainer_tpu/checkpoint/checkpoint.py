"""Checkpoint I/O: per-epoch model export + full training-state save/resume.

The reference saves only model weights every epoch on rank 0 and pickles the
history once at the end (ref: src/trainer.py:232-241, 252-256); ``fit()``
cannot resume.  Here both layers exist:

* ``save_model_variables`` / ``load_model_variables`` — weights-only export
  (``model.msgpack``, the ``model.pth`` analog) for inference and the
  03-notebook flow.
* ``save_checkpoint`` / ``restore_checkpoint`` — the full TrainState
  (params, optimizer state, step, PRNG key, batch_stats) plus history, so a
  preempted TPU job resumes exactly — the deliberate extension called out in
  SURVEY.md §5.

Checkpoint format (v2): a ``checkpoint_<epoch>/`` DIRECTORY holding one
``.npy`` file per state leaf plus a JSON manifest — per-leaf, streamed
writes that scale to GPT-2-class states (the v1 monolithic pickle
double-buffered ~1.5GB in RAM and executed arbitrary bytes on load;
``.npy`` restores with ``allow_pickle=False``).  Writes are atomic
(tmp dir + rename), host-0-only at the call sites matching the reference's
rank-0 gate (ref: src/trainer.py:252-254), and optionally asynchronous:
``save_checkpoint(..., block=False)`` snapshots device→host synchronously
(the compiled step donates state buffers, so references alone would go
stale) and hands the disk writes to a single background writer thread so
the training loop isn't stalled by I/O.  Legacy v1 ``.pkl`` checkpoints
remain readable.
"""

from __future__ import annotations

import copy
import hashlib
import io
import json
import os
import pickle
import re
import shutil
import threading
import zlib
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, List, Optional, Tuple

import jax
import numpy as np
from flax import serialization

MODEL_FILE = "model.msgpack"
CHECKPOINT_PREFIX = "checkpoint_"
MANIFEST = "manifest.json"
CORRUPT_SUFFIX = ".corrupt"
_CKPT_RE = re.compile(rf"^{CHECKPOINT_PREFIX}(\d+)(\.pkl)?$")


class CheckpointCorrupt(ValueError):
    """A checkpoint failed integrity verification (missing leaf files,
    unreadable manifest, or a CRC32 mismatch against the manifest)."""

# One writer thread: checkpoint writes are ordered (epoch N lands before
# N+1) and never overlap, while the training loop keeps running.
_writer = ThreadPoolExecutor(max_workers=1, thread_name_prefix="ckpt-writer")
_pending: List[Future] = []
_pending_lock = threading.Lock()


def _atomic_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as fp:
        fp.write(data)
    os.replace(tmp, path)


def save_model_variables(model_dir: str, variables: Any) -> str:
    """Weights-only export, every-epoch cadence (ref: src/trainer.py:232-235)."""
    return write_model_bytes(
        model_dir, serialization.to_bytes(fetch_to_host(variables))
    )


def write_model_bytes(model_dir: str, data: bytes) -> str:
    """Write an already-serialized export — lets a caller exporting to two
    places (every-epoch + best) fetch and serialize once."""
    os.makedirs(model_dir, exist_ok=True)
    path = os.path.join(model_dir, MODEL_FILE)
    _atomic_write(path, data)
    return path


def load_model_variables(path: str) -> Any:
    """Template-free restore of a ``model.msgpack`` into nested dicts."""
    if os.path.isdir(path):
        path = os.path.join(path, MODEL_FILE)
    with open(path, "rb") as fp:
        return serialization.msgpack_restore(fp.read())


# ------------------------------------------------------- weights fingerprint
MODEL_MANIFEST = "model_manifest.json"


def _fingerprint_rows(variables: Any):
    """Sorted (path, shape, dtype, crc32) rows over the variables tree.
    Goes through ``to_state_dict`` so FrozenDict / plain-dict / msgpack-
    restored trees of the same weights hash identically."""
    state = serialization.to_state_dict(variables)
    for path, leaf in _flatten(state):
        if leaf is None or (isinstance(leaf, dict) and not leaf):
            continue
        arr = np.asarray(jax.device_get(leaf))
        yield ("/".join(path), str(arr.shape), str(arr.dtype),
               _crc32(arr.tobytes()))


def weights_structure_digest(variables: Any) -> str:
    """The config-hash half of the fingerprint: sha1 over the sorted
    (path | shape | dtype) rows — two checkpoints of the same
    architecture share it even when their values differ."""
    h = hashlib.sha1()
    for path, shape, dtype, _ in _fingerprint_rows(variables):
        h.update(f"{path}|{shape}|{dtype}\n".encode())
    return f"cfg:{h.hexdigest()[:16]}"


def weights_fingerprint(variables: Any) -> str:
    """Identity of a concrete set of weights: sha1 over the sorted
    (path | shape | dtype | crc32(leaf bytes)) rows.  Recorded in
    export manifests and carried by every ``KVSlotExport`` — KV is not
    portable across weights, so migration refuses adoption when the
    fingerprints differ (serving/transfer.py ``WeightsMismatch``)."""
    h = hashlib.sha1()
    for path, shape, dtype, crc in _fingerprint_rows(variables):
        h.update(f"{path}|{shape}|{dtype}|{crc:#010x}\n".encode())
    return f"w:{h.hexdigest()[:16]}"


def write_model_manifest(model_dir: str, variables: Any,
                         data: Optional[bytes] = None) -> dict:
    """``model_manifest.json`` next to ``model.msgpack``: the weights
    fingerprint + structure digest (and the serialized blob's CRC32
    when the caller has the bytes in hand).  Returns the manifest."""
    os.makedirs(model_dir, exist_ok=True)
    manifest = {
        "format": 1,
        "weights_fingerprint": weights_fingerprint(variables),
        "structure_digest": weights_structure_digest(variables),
    }
    if data is not None:
        manifest["model_crc32"] = _crc32(data)
        manifest["model_bytes"] = len(data)
    _atomic_write(
        os.path.join(model_dir, MODEL_MANIFEST),
        json.dumps(manifest, indent=1).encode(),
    )
    return manifest


def load_model_manifest(path: str) -> Optional[dict]:
    """The export manifest of a model dir (or of ``model.msgpack``'s
    parent), or None for pre-manifest exports."""
    if not os.path.isdir(path):
        path = os.path.dirname(path) or "."
    try:
        with open(os.path.join(path, MODEL_MANIFEST)) as fp:
            return json.load(fp)
    except (OSError, ValueError):
        return None


# ----------------------------------------------------------- v2 leaf format
def _flatten(tree: Any, path=()):
    """(path tuple, leaf) pairs over the nested state dict, sorted keys.
    Empty dicts (optax EmptyState, empty batch_stats) are themselves leaves —
    dropping them would change the state-dict structure on restore."""
    if isinstance(tree, dict) and tree:
        for key in sorted(tree):
            yield from _flatten(tree[key], path + (str(key),))
    else:
        yield path, tree


def _unflatten(pairs) -> Any:
    root: dict = {}
    for path, leaf in pairs:
        node = root
        for key in path[:-1]:
            node = node.setdefault(key, {})
        node[path[-1]] = leaf
    return root


def _npy_bytes(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, arr, allow_pickle=False)
    return buf.getvalue()


def _crc32(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def _apply_ckpt_faults(final_dir: str, epoch: int) -> None:
    """``ckpt_truncate`` injection hook (resilience/faults.py): truncate
    the largest leaf/piece file of a just-committed checkpoint, the
    storage-corruption mode only CRC verification catches."""
    from ml_trainer_tpu.resilience.faults import active_plan

    plan = active_plan()
    if plan is None or plan.fire("ckpt_truncate", epoch=epoch) is None:
        return
    npys = [
        os.path.join(final_dir, n)
        for n in os.listdir(final_dir)
        if n.endswith(".npy")
    ]
    if not npys:
        return
    victim = max(npys, key=os.path.getsize)
    size = os.path.getsize(victim)
    with open(victim, "r+b") as fp:
        fp.truncate(max(size // 2, 1))


def state_mesh_topology(state: Any) -> Optional[dict]:
    """Topology of the mesh that holds ``state`` (axis names/sizes,
    device and process counts), from the first leaf carrying a
    ``NamedSharding`` — recorded in every checkpoint manifest and in
    ``PREEMPTED.json`` so a restore at a DIFFERENT topology knows (and
    can report) the shape of the world that wrote the checkpoint.
    None for host-only states (nothing placed on a mesh yet)."""
    for leaf in jax.tree.leaves(state):
        sharding = getattr(leaf, "sharding", None)
        if isinstance(sharding, jax.sharding.NamedSharding):
            mesh = sharding.mesh
            return {
                "axes": {str(a): int(s) for a, s in mesh.shape.items()},
                "device_count": int(mesh.size),
                "process_count": int(jax.process_count()),
            }
    return None


def checkpoint_topology(path: str) -> Optional[dict]:
    """The ``mesh`` record of a v2/v3 checkpoint manifest (the topology
    that WROTE it), or None (v1 pickles, pre-topology checkpoints)."""
    if not os.path.isdir(path):
        return None
    try:
        with open(os.path.join(path, MANIFEST)) as fp:
            return json.load(fp).get("mesh")
    except (OSError, ValueError):
        return None


def _write_checkpoint_dir(
    final_dir: str, state_dict: Any, history: dict, epoch: int,
    mesh: Optional[dict] = None,
) -> None:
    # The ACTUAL checkpoint I/O (often on the async writer thread): the
    # span shows on the Perfetto timeline whether the write hides behind
    # the next epoch or stalls it (telemetry/spans.py).
    from ml_trainer_tpu.telemetry.spans import span as _span

    with _span("ckpt_write_io", epoch=epoch, dir=os.path.basename(final_dir)):
        _write_checkpoint_dir_inner(final_dir, state_dict, history, epoch,
                                    mesh)


def _write_checkpoint_dir_inner(
    final_dir: str, state_dict: Any, history: dict, epoch: int,
    mesh: Optional[dict] = None,
) -> None:
    tmp_dir = final_dir + ".tmp"
    if os.path.isdir(tmp_dir):
        shutil.rmtree(tmp_dir)
    os.makedirs(tmp_dir)
    leaves = []
    for i, (path, leaf) in enumerate(_flatten(state_dict)):
        if isinstance(leaf, dict):  # empty container leaf
            leaves.append({"path": list(path), "empty": True})
            continue
        if leaf is None:  # e.g. TrainState.ema_params with EMA disabled
            leaves.append({"path": list(path), "none": True})
            continue
        arr = np.asarray(jax.device_get(leaf))
        fname = f"leaf_{i:05d}.npy"
        # Serialize to memory first so the manifest records each file's
        # CRC32 — restore and verify_checkpoint check it, which is what
        # turns silent bit-rot/truncation into a quarantined checkpoint
        # instead of a corrupted resume.
        data = _npy_bytes(arr)
        with open(os.path.join(tmp_dir, fname), "wb") as fp:
            fp.write(data)
        leaves.append({"path": list(path), "file": fname, "crc32": _crc32(data)})
    manifest = {
        "format": 2,
        "epoch": epoch,
        "history": history,
        "leaves": leaves,
        # Topology of the writing mesh (elastic restore reads it to name
        # source vs target axes in reshard errors; None pre-placement).
        "mesh": mesh,
        # Identity of the weights inside this checkpoint — what a
        # serving deploy compares before adopting migrated KV.
        "weights_fingerprint": (
            weights_fingerprint({"params": state_dict["params"]})
            if isinstance(state_dict, dict) and "params" in state_dict
            else None
        ),
    }
    with open(os.path.join(tmp_dir, MANIFEST), "w") as fp:
        json.dump(manifest, fp)
    if os.path.isdir(final_dir):
        shutil.rmtree(final_dir)
    os.replace(tmp_dir, final_dir)
    _apply_ckpt_faults(final_dir, epoch)


def wait_for_checkpoints() -> None:
    """Join all in-flight async checkpoint writes, re-raising any failure."""
    with _pending_lock:
        pending, _pending[:] = list(_pending), []
    for fut in pending:
        fut.result()


def fetch_to_host(tree: Any) -> Any:
    """Device→host snapshot that survives host-spanning shardings.

    ``jax.device_get`` raises on arrays that are not fully addressable
    (e.g. ZeRO-1 optimizer moments sharded over a multi-host ``data``
    axis); those are gathered across processes.  Fully-REPLICATED
    multi-host leaves (params under pure DP) read their local replica
    instead: no collective — which also means a host can export weights
    while other hosts sit in an unrelated barrier (process_allgather
    launches a global computation, so a host-0-only call would otherwise
    deadlock against any concurrent collective; observed exactly so with
    the v3 commit barrier).  Single-host arrays take the plain fast
    path."""
    def fetch(leaf):
        if hasattr(leaf, "is_fully_addressable") and not leaf.is_fully_addressable:
            if getattr(leaf, "is_fully_replicated", False):
                return np.asarray(leaf.addressable_data(0))
            from jax.experimental import multihost_utils

            return np.asarray(
                multihost_utils.process_allgather(leaf, tiled=True)
            )
        return jax.device_get(leaf)

    return jax.tree.map(fetch, tree)


def save_checkpoint(
    ckpt_dir: str,
    state: Any,
    history: dict,
    epoch: int,
    keep: int = 3,
    block: bool = True,
) -> str:
    """Write ``checkpoint_<epoch>/``.  With ``block=False`` the device→host
    snapshot happens synchronously (the training step may DONATE the state
    buffers, so the device arrays can be invalid by the next step) and only
    the disk writes run on the background writer thread; call
    ``wait_for_checkpoints()`` (the trainer does at fit-end) to surface
    errors."""
    os.makedirs(ckpt_dir, exist_ok=True)
    mesh = state_mesh_topology(state)  # before the fetch drops shardings
    state_dict = fetch_to_host(serialization.to_state_dict(state))
    # Deep-copy on the caller's thread: the trainer hands us its LIVE
    # history lists, which the next epoch mutates while the writer runs.
    history = copy.deepcopy(history)
    path = os.path.join(ckpt_dir, f"{CHECKPOINT_PREFIX}{epoch}")

    def job():
        _write_checkpoint_dir(path, state_dict, history, epoch, mesh)
        prune_checkpoints(ckpt_dir, keep)

    if block:
        job()
    else:
        fut = _writer.submit(job)
        with _pending_lock:
            _pending.append(fut)
    return path


# ------------------------------------------------------- v3 sharded format
# Each process writes exactly its addressable shards — no process ever
# gathers (or even holds) the full state tree, which is what makes
# GPT-2-class ZeRO-1/TP states checkpointable without the host-0 RAM
# spike + DCN allgather of ``fetch_to_host``.  Layout of
# ``checkpoint_<epoch>/``:
#
#   leaf_<i>_s<j>_p<proc>.npy   one saved piece (a device shard) of leaf i
#   manifest_p<proc>.json       piece table of process <proc>
#   manifest.json               commit marker, written LAST by process 0:
#                               format=3, epoch, history, leaf tree
#                               (paths + global shapes + dtypes)
#
# The format assumes the checkpoint directory is shared storage (GCS/NFS —
# the normal TPU-pod setup, and the reason restore can stitch every
# process's pieces).  Restore reads each leaf back either as a full host
# array (shardings=None) or directly into a sharded ``jax.Array`` via
# ``make_array_from_callback`` — each device materializes only its own
# slice, stitched from whatever saved pieces intersect it, so a checkpoint
# written on mesh A restores onto a DIFFERENT mesh B (elastic resume: the
# piece grid and the target shard grid need not match).


def _piece_entries(leaf) -> Optional[list]:
    """The (index, data) pieces THIS process must write for a leaf, or
    None when the leaf is a host-side value (process 0 writes those whole).
    Replicated shards are deduped by ``replica_id == 0`` — exactly one
    process in the cluster owns each distinct piece."""
    if not hasattr(leaf, "addressable_shards"):
        return None
    out = []
    for shard in leaf.addressable_shards:
        if shard.replica_id != 0:
            continue
        out.append((shard.index, np.asarray(shard.data)))
    return out


def _index_bounds(index, shape) -> Tuple[list, list]:
    """Normalize a shard index (tuple of slices) to explicit start/stop."""
    starts, stops = [], []
    for sl, dim in zip(index, shape):
        lo, hi, step = sl.indices(dim)
        assert step == 1, f"strided shard index {sl} unsupported"
        starts.append(lo)
        stops.append(hi)
    return starts, stops


def save_checkpoint_sharded(
    ckpt_dir: str,
    state: Any,
    history: dict,
    epoch: int,
    keep: int = 3,
    block: bool = True,
) -> str:
    """Write ``checkpoint_<epoch>/`` with every process contributing its
    addressable shards (format v3).  COLLECTIVE: every process must call
    it (there is a cross-process barrier before the commit marker).

    ``block=False`` keeps only the disk writes off the training thread —
    the device→host shard snapshot is synchronous regardless (the compiled
    step donates state buffers).  In a multi-process cluster the call is
    forced synchronous: the commit barrier is a collective, and collectives
    must not run on a background thread concurrently with the training
    step's.
    """
    os.makedirs(ckpt_dir, exist_ok=True)
    proc = jax.process_index()
    nproc = jax.process_count()
    if nproc > 1:
        block = True
    mesh = state_mesh_topology(state)
    state_dict = serialization.to_state_dict(state)
    final_dir = os.path.join(ckpt_dir, f"{CHECKPOINT_PREFIX}{epoch}")
    history = copy.deepcopy(history)

    # Snapshot (synchronously) the pieces this process owns, and — on
    # process 0 — the leaf-tree metadata for the commit manifest.
    my_pieces: list = []   # (leaf_id, [(starts, stops, np.ndarray), ...])
    leaf_meta: list = []
    for i, (path, leaf) in enumerate(_flatten(state_dict)):
        if isinstance(leaf, dict):
            leaf_meta.append({"path": list(path), "empty": True})
            continue
        if leaf is None:
            leaf_meta.append({"path": list(path), "none": True})
            continue
        pieces = _piece_entries(leaf)
        if pieces is None:  # host-side scalar/ndarray: process 0 owns it
            arr = np.asarray(leaf)
            pieces = (
                [(tuple(slice(0, d) for d in arr.shape), arr)]
                if proc == 0 else []
            )
            shape, dtype = arr.shape, arr.dtype
        else:
            shape, dtype = leaf.shape, leaf.dtype
        leaf_meta.append({
            "path": list(path),
            "shape": list(shape),
            "dtype": np.dtype(dtype).name,
        })
        entries = []
        for j, (index, data) in enumerate(pieces):
            starts, stops = _index_bounds(index, shape)
            entries.append((j, starts, stops, data))
        if entries:
            my_pieces.append((i, entries))

    def write_files():
        os.makedirs(final_dir, exist_ok=True)
        # A re-save of the same epoch can target a directory that already
        # carries a committed manifest (fit() re-run without resume).
        # np.save overwrites are not atomic, so the stale commit marker
        # must die BEFORE the first piece file is torn open: a crash
        # mid-save then leaves an uncommitted directory (invisible to
        # restore) instead of a valid marker over mixed/torn pieces.
        # The barrier keeps every other process's writes behind the
        # unlink — manifest-last on save, manifest-first on invalidate.
        if proc == 0:
            try:
                os.unlink(os.path.join(final_dir, MANIFEST))
            except FileNotFoundError:
                pass
        if nproc > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(
                f"ckpt_v3_invalidate_{epoch}"
            )
        table = []
        for leaf_id, entries in my_pieces:
            for j, starts, stops, data in entries:
                fname = f"leaf_{leaf_id:05d}_s{j}_p{proc:05d}.npy"
                raw = _npy_bytes(data)
                with open(os.path.join(final_dir, fname), "wb") as fp:
                    fp.write(raw)
                table.append({
                    "leaf": leaf_id, "file": fname,
                    "start": starts, "stop": stops,
                    "crc32": _crc32(raw),
                })
        _atomic_write(
            os.path.join(final_dir, f"manifest_p{proc:05d}.json"),
            json.dumps({"process": proc, "pieces": table}).encode(),
        )

    def commit():
        if nproc > 1:
            from jax.experimental import multihost_utils

            # Every process's shard files + piece table are on disk
            # before the marker makes the checkpoint discoverable.
            multihost_utils.sync_global_devices(f"ckpt_v3_commit_{epoch}")
        if proc == 0:
            _atomic_write(
                os.path.join(final_dir, MANIFEST),
                json.dumps({
                    "format": 3,
                    "epoch": epoch,
                    "history": history,
                    "process_count": nproc,
                    "leaves": leaf_meta,
                    "mesh": mesh,
                }).encode(),
            )
            _apply_ckpt_faults(final_dir, epoch)
            prune_checkpoints(ckpt_dir, keep)

    if block:
        write_files()
        commit()
    else:
        fut = _writer.submit(lambda: (write_files(), commit()))
        with _pending_lock:
            _pending.append(fut)
    return final_dir


def _read_piece_tables(path: str, nproc: Optional[int] = None) -> dict:
    """leaf_id -> [(starts, stops, file)] over the piece tables of
    processes [0, nproc).  ``nproc`` comes from the COMMIT manifest: an
    interrupted earlier save by a larger cluster can leave stale
    ``manifest_p*``/piece files in the same directory (the fresh save
    atomically overwrites the indices it reuses but cannot know about
    higher ones), and merging those would silently corrupt the restore —
    last-writer-wins in ``_stitch``.  Stale piece FILES are harmless:
    only files referenced by a read table are ever opened."""
    tables: dict = {}
    names = (
        [
            n for n in sorted(os.listdir(path))
            if n.startswith("manifest_p") and n.endswith(".json")
        ]
        if nproc is None
        else [f"manifest_p{p:05d}.json" for p in range(nproc)]
    )
    for name in names:
        with open(os.path.join(path, name)) as fp:
            for e in json.load(fp)["pieces"]:
                tables.setdefault(e["leaf"], []).append(
                    (e["start"], e["stop"], e["file"], e.get("crc32"))
                )
    return tables


def _stitch(path, pieces, starts, stops, shape, dtype):
    """Materialize the [starts, stops) sub-box of a leaf from the saved
    pieces that intersect it.  Pieces are read through ``np.load``
    memmaps, so only the intersecting pages come off storage — a device
    restoring 1/Nth of a leaf reads ~1/Nth of its bytes."""
    box = np.empty(
        [hi - lo for lo, hi in zip(starts, stops)], dtype=dtype
    )
    filled = np.zeros(box.shape, dtype=bool)
    for p_starts, p_stops, fname, _crc in pieces:
        inter_lo = [max(a, b) for a, b in zip(starts, p_starts)]
        inter_hi = [min(a, b) for a, b in zip(stops, p_stops)]
        if any(lo >= hi for lo, hi in zip(inter_lo, inter_hi)):
            continue
        src = np.load(
            os.path.join(path, fname), allow_pickle=False, mmap_mode="r"
        )
        src_sel = tuple(
            slice(lo - plo, hi - plo)
            for lo, hi, plo in zip(inter_lo, inter_hi, p_starts)
        )
        dst_sel = tuple(
            slice(lo - blo, hi - blo)
            for lo, hi, blo in zip(inter_lo, inter_hi, starts)
        )
        box[dst_sel] = src[src_sel]
        filled[dst_sel] = True
    if not np.all(filled):
        raise ValueError(
            f"checkpoint pieces do not cover [{starts}, {stops}) of a "
            f"{shape} leaf — incomplete or corrupt v3 checkpoint"
        )
    return box


def _restore_v3(path: str, manifest: dict, state_template: Any, shardings):
    tables = _read_piece_tables(path, manifest.get("process_count"))
    shard_leaves = (
        None if shardings is None
        else {
            tuple(str(k) for k in p): s
            for p, s in _flatten(serialization.to_state_dict(shardings))
        }
    )
    pairs = []
    for i, meta in enumerate(manifest["leaves"]):
        lpath = tuple(meta["path"])
        if meta.get("empty"):
            pairs.append((lpath, {}))
            continue
        if meta.get("none"):
            pairs.append((lpath, None))
            continue
        shape = tuple(meta["shape"])
        dtype = np.dtype(meta["dtype"])
        pieces = tables.get(i, [])
        sharding = shard_leaves.get(lpath) if shard_leaves else None
        if isinstance(sharding, jax.sharding.NamedSharding):
            # Elastic restore onto a DIFFERENT mesh: fail with a
            # structured error naming the saved vs target axes when a
            # saved shape does not divide the new mesh — BEFORE any
            # device allocates (the alternative is an opaque reshape
            # traceback out of make_array_from_callback).
            from ml_trainer_tpu.resilience.elastic import (
                ReshardError,
                _spec_axis_size,
            )

            target = {
                "axes": {
                    str(a): int(s) for a, s in sharding.mesh.shape.items()
                },
                "device_count": int(sharding.mesh.size),
            }
            for dim, entry in enumerate(tuple(sharding.spec)[:len(shape)]):
                if entry is None:
                    continue
                n = _spec_axis_size(entry, sharding.mesh)
                if n > 1 and shape[dim] % n:
                    raise ReshardError(
                        leaf="/".join(lpath), dim=dim, size=shape[dim],
                        axes=entry, axis_size=n,
                        source_topology=manifest.get("mesh"),
                        target_topology=target,
                    )
        if sharding is not None and isinstance(
            sharding, jax.sharding.Sharding
        ):
            def cb(index, _pieces=pieces, _shape=shape, _dtype=dtype):
                starts, stops = _index_bounds(index, _shape)
                return _stitch(path, _pieces, starts, stops, _shape, _dtype)

            leaf = jax.make_array_from_callback(shape, sharding, cb)
        else:
            leaf = _stitch(
                path, pieces, [0] * len(shape), list(shape), shape, dtype
            )
        pairs.append((lpath, leaf))
    state = _from_state_dict_compat(state_template, _unflatten(pairs))
    return state, manifest["history"], manifest["epoch"]


def _scan_checkpoints(ckpt_dir: str):
    """Sorted (epoch, filename) pairs of checkpoints (v1 pkls, v2 dirs,
    v3 sharded dirs).  In-flight ``.tmp`` dirs are skipped, and so are
    directories without a committed ``manifest.json`` — a v3 save writes
    shard files first and the manifest LAST (the commit marker), so an
    interrupted multi-process save never looks like a valid checkpoint."""
    if not os.path.isdir(ckpt_dir):
        return []
    found = []
    for name in os.listdir(ckpt_dir):
        m = _CKPT_RE.match(name)
        if not m:
            continue
        full = os.path.join(ckpt_dir, name)
        if os.path.isdir(full) and not os.path.exists(
            os.path.join(full, MANIFEST)
        ):
            continue
        found.append((int(m.group(1)), name))
    return sorted(found)


def prune_checkpoints(ckpt_dir: str, keep: int) -> None:
    if not keep:
        return
    for _, name in _scan_checkpoints(ckpt_dir)[:-keep]:
        full = os.path.join(ckpt_dir, name)
        if os.path.isdir(full):
            shutil.rmtree(full)
        else:
            os.remove(full)


def latest_checkpoint(ckpt_dir: str) -> Optional[str]:
    found = _scan_checkpoints(ckpt_dir)
    if not found:
        return None
    return os.path.join(ckpt_dir, found[-1][1])


def checkpoint_format(path: str) -> int:
    """1 (legacy pickle), 2 (per-leaf dir), or 3 (per-host sharded)."""
    if not os.path.isdir(path):
        return 1
    with open(os.path.join(path, MANIFEST)) as fp:
        return int(json.load(fp).get("format", 2))


def _verify_file(path: str, crc: Optional[int]) -> None:
    """One leaf/piece file: exists, and matches its recorded CRC32 (files
    written before CRCs existed only get the existence/parse check)."""
    if not os.path.exists(path):
        raise CheckpointCorrupt(f"missing checkpoint file: {path}")
    if crc is None:
        try:  # pre-CRC checkpoint: at least require a parseable header
            np.load(path, allow_pickle=False, mmap_mode="r")
        except Exception as e:
            raise CheckpointCorrupt(f"unreadable leaf {path}: {e}") from e
        return
    with open(path, "rb") as fp:
        if _crc32(fp.read()) != crc:
            raise CheckpointCorrupt(
                f"CRC32 mismatch for {path} (truncated or bit-rotted)"
            )


def verify_checkpoint(path: str) -> None:
    """Integrity-check one checkpoint; raises ``CheckpointCorrupt`` on any
    failure.  v2/v3 directories verify the manifest plus every referenced
    leaf/piece file against its recorded CRC32; legacy v1 pickles only get
    an existence/size check (their format predates integrity records)."""
    if not os.path.isdir(path):
        if not os.path.exists(path) or os.path.getsize(path) == 0:
            raise CheckpointCorrupt(f"missing or empty checkpoint: {path}")
        return
    try:
        with open(os.path.join(path, MANIFEST)) as fp:
            manifest = json.load(fp)
    except (OSError, ValueError) as e:
        raise CheckpointCorrupt(f"unreadable manifest in {path}: {e}") from e
    if manifest.get("format") == 3:
        try:
            tables = _read_piece_tables(path, manifest.get("process_count"))
        except (OSError, ValueError, KeyError) as e:
            raise CheckpointCorrupt(
                f"unreadable piece tables in {path}: {e}"
            ) from e
        for pieces in tables.values():
            for entry in pieces:
                _verify_file(os.path.join(path, entry[2]), entry[3])
        return
    for leaf in manifest.get("leaves", []):
        if leaf.get("empty") or leaf.get("none"):
            continue
        _verify_file(
            os.path.join(path, leaf["file"]), leaf.get("crc32")
        )


def quarantine_checkpoint(path: str) -> str:
    """Move a corrupt checkpoint aside as ``<name>.corrupt`` (out of the
    ``checkpoint_<n>`` namespace, so scans/prunes/restores never see it
    again) and return the new path.  Idempotent-ish: an existing
    quarantine of the same name is replaced."""
    target = path + CORRUPT_SUFFIX
    if os.path.isdir(target):
        shutil.rmtree(target)
    elif os.path.exists(target):
        os.remove(target)
    os.replace(path, target)
    return target


def latest_valid_checkpoint(
    ckpt_dir: str, quarantine: bool = True
) -> Optional[str]:
    """Newest checkpoint that passes ``verify_checkpoint``, scanning
    newest→oldest.  Failing checkpoints are quarantined (renamed
    ``*.corrupt``) so the next scan skips them without re-reading; pass
    ``quarantine=False`` to leave them in place (e.g. non-primary hosts
    on shared storage — exactly one process should move directories)."""
    from ml_trainer_tpu.utils.logging import get_logger

    logger = get_logger("ml_trainer_tpu.checkpoint")
    for _, name in reversed(_scan_checkpoints(ckpt_dir)):
        full = os.path.join(ckpt_dir, name)
        try:
            verify_checkpoint(full)
            return full
        except CheckpointCorrupt as e:
            if quarantine:
                moved = quarantine_checkpoint(full)
                logger.warning(
                    f"Corrupt checkpoint quarantined: {full} -> {moved} "
                    f"({e}); falling back to the previous checkpoint."
                )
            else:
                logger.warning(
                    f"Corrupt checkpoint skipped: {full} ({e}); falling "
                    "back to the previous checkpoint."
                )
    return None


def _reconcile_ema(state_template: Any, saved: Any) -> Any:
    """Make checkpoints portable across the ``ema_decay`` setting (and
    across its addition to TrainState).  Missing/None EMA + EMA-enabled
    template → seed the EMA from the saved params; EMA in the checkpoint +
    EMA-disabled template → drop it; pre-EMA checkpoints → inject None."""
    if not isinstance(saved, dict):
        return saved
    tpl = serialization.to_state_dict(state_template)
    if not (isinstance(tpl, dict) and "ema_params" in tpl):
        return saved
    want_ema = tpl["ema_params"] is not None
    have = saved.get("ema_params")
    if want_ema and have is None:
        if "params" not in saved:
            raise ValueError(
                "Cannot seed EMA from checkpoint: it has no 'params' entry "
                f"(found keys {sorted(saved)}) — the checkpoint is malformed."
            )
        # EMA turned on for (or added to) this run: start it at the
        # restored params, exactly how a fresh Trainer seeds it.  Aliasing
        # the host arrays is fine — restore only reads them, and
        # device_put gives each leaf its own device buffer.
        saved = dict(saved)
        saved["ema_params"] = saved["params"]
    elif not want_ema:
        saved = dict(saved)
        saved["ema_params"] = None
    return saved


def _reconcile_guard_counters(state_template: Any, saved: Any) -> Any:
    """Make checkpoints portable across the scalar-counter additions to
    TrainState (skipped_steps / bad_streak, and the mixed-precision
    loss_scale / good_steps).  Pre-counter checkpoints restoring into a
    counter-carrying template get neutral defaults (the trainer re-seeds
    a zero loss_scale to its configured initial scale); counter-carrying
    checkpoints restoring into a counter-less template (states built
    outside the Trainer, or an fp32 resume of a bf16 run) drop them."""
    if not isinstance(saved, dict):
        return saved
    tpl = serialization.to_state_dict(state_template)
    if not isinstance(tpl, dict):
        return saved
    defaults = {
        "skipped_steps": lambda: np.zeros((), np.int32),
        "bad_streak": lambda: np.zeros((), np.int32),
        "loss_scale": lambda: np.zeros((), np.float32),
        "good_steps": lambda: np.zeros((), np.int32),
    }
    for key, default in defaults.items():
        if key not in tpl:
            continue
        want = tpl[key] is not None
        if want and saved.get(key) is None:
            saved = dict(saved)
            saved[key] = default()
        elif not want and key in saved and saved[key] is not None:
            saved = dict(saved)
            saved[key] = None
        elif key not in saved:
            saved = dict(saved)
            saved[key] = None
    return saved


def _inject_masked_levels(template_sd: Any, saved: Any) -> Any:
    """Align a pre-decay-mask opt_state dict with a template that carries
    ``optax.masked`` wrappers: wherever the template expects the
    single-key ``{'inner_state': ...}`` level (MaskedState) and the saved
    dict holds the bare inner state, inject the level.  Purely structural
    — leaf values are untouched."""
    if isinstance(template_sd, dict):
        t_keys = set(template_sd.keys())
        saved_is_masked = isinstance(saved, dict) and set(
            saved.keys()
        ) == {"inner_state"}
        if t_keys == {"inner_state"} and not saved_is_masked:
            return {
                "inner_state": _inject_masked_levels(
                    template_sd["inner_state"], saved
                )
            }
        if isinstance(saved, dict):
            return {
                k: (
                    _inject_masked_levels(template_sd[k], v)
                    if k in template_sd else v
                )
                for k, v in saved.items()
            }
    return saved


def _from_state_dict_compat(state_template: Any, saved: Any) -> Any:
    """``from_state_dict`` with fallbacks for checkpoints written by older
    trainer versions: (a) before every optimizer was wrapped in
    ``chain(clip-or-identity, inner)`` — re-nest under the template's
    ``{'0': {}, '1': inner}`` shape; (b) before a weight-decay mask was
    always passed — inject the ``MaskedState`` ``inner_state`` levels the
    new opt_state carries.  Retried in combination; on failure the
    ORIGINAL mismatch is re-raised (e.g. optimizer changed between save
    and resume — the real story, not a fallback's secondary failure)."""
    saved = _reconcile_ema(state_template, saved)
    saved = _reconcile_guard_counters(state_template, saved)
    try:
        return serialization.from_state_dict(state_template, saved)
    except (ValueError, KeyError, AttributeError) as orig:
        if not (isinstance(saved, dict) and "opt_state" in saved):
            raise
        template_sd = serialization.to_state_dict(state_template)
        candidates = []
        renested = {"0": {}, "1": saved["opt_state"]}
        for opt_sd in (saved["opt_state"], renested):
            candidates.append(opt_sd)
            candidates.append(
                _inject_masked_levels(template_sd.get("opt_state"), opt_sd)
            )
        for opt_sd in candidates[1:]:  # [0] is what already failed
            wrapped = dict(saved)
            wrapped["opt_state"] = opt_sd
            try:
                return serialization.from_state_dict(state_template, wrapped)
            except Exception:
                continue
        raise orig


def restore_checkpoint(
    path: str, state_template: Any, shardings: Any = None
) -> Tuple[Any, dict, int]:
    """Restore (state, history, epoch); the template supplies pytree
    structure (the trainer always has one before restoring).

    ``shardings`` (a pytree of ``NamedSharding`` matching the state, or
    None) applies to v3 sharded checkpoints: each leaf is built directly
    as a sharded ``jax.Array`` on the target mesh — which may differ from
    the mesh that wrote the checkpoint (elastic resume).  v1/v2
    checkpoints ignore it and return host arrays (the caller re-places
    them, which equally works across meshes — every leaf is full there)."""
    if os.path.isdir(path):
        with open(os.path.join(path, MANIFEST)) as fp:
            manifest = json.load(fp)
        if manifest.get("format") == 3:
            return _restore_v3(path, manifest, state_template, shardings)
        def load_leaf(leaf):
            full = os.path.join(path, leaf["file"])
            crc = leaf.get("crc32")
            if crc is None:  # pre-CRC checkpoint
                return np.load(full, allow_pickle=False)
            with open(full, "rb") as fp:
                data = fp.read()
            if _crc32(data) != crc:
                raise CheckpointCorrupt(
                    f"CRC32 mismatch for {full} (truncated or bit-rotted); "
                    "restore from an earlier checkpoint "
                    "(latest_valid_checkpoint quarantines and falls back)"
                )
            return np.load(io.BytesIO(data), allow_pickle=False)

        pairs = [
            (
                tuple(leaf["path"]),
                {}
                if leaf.get("empty")
                else None
                if leaf.get("none")
                else load_leaf(leaf),
            )
            for leaf in manifest["leaves"]
        ]
        state = _from_state_dict_compat(state_template, _unflatten(pairs))
        return state, manifest["history"], manifest["epoch"]
    # Legacy v1 monolithic pickle (round-1 checkpoints).
    with open(path, "rb") as fp:
        payload = pickle.load(fp)
    state = _from_state_dict_compat(state_template, payload["state"])
    return state, payload["history"], payload["epoch"]
