from ml_trainer_tpu.checkpoint.checkpoint import (
    CHECKPOINT_PREFIX,
    MODEL_FILE,
    checkpoint_format,
    fetch_to_host,
    latest_checkpoint,
    load_model_variables,
    prune_checkpoints,
    restore_checkpoint,
    save_checkpoint,
    save_checkpoint_sharded,
    save_model_variables,
    write_model_bytes,
    wait_for_checkpoints,
)
from ml_trainer_tpu.checkpoint.torch_import import load_torch_checkpoint
from ml_trainer_tpu.checkpoint.torch_export import save_torch_checkpoint

__all__ = [
    "CHECKPOINT_PREFIX",
    "MODEL_FILE",
    "checkpoint_format",
    "fetch_to_host",
    "latest_checkpoint",
    "load_model_variables",
    "prune_checkpoints",
    "restore_checkpoint",
    "save_checkpoint",
    "save_checkpoint_sharded",
    "save_model_variables",
    "write_model_bytes",
    "wait_for_checkpoints",
    "load_torch_checkpoint",
    "save_torch_checkpoint",
]
