"""Host-wide TPU tunnel mutex.

The axon tunnel on this host serializes sessions; concurrent dials are
the leading suspect for its recurring wedge (r3/r4: hand sessions
succeeded while the driver's bench — racing the background watcher's
probes — got nothing but init hangs).  EVERY tunnel client serializes on
one flock:

- Python clients (``bench.py``, ``scripts/bench_decode.py``) call
  :func:`acquire_tunnel_lock` before the first ``jax.devices()``.
- Shell clients (``scripts/tpu_watch.sh`` probes, ``tpu_recover.sh``
  stages) use ``flock(1)`` on the same path and write their identity
  into the sidecar holder file.
- A parent that already holds the lock exports
  ``TPU_TUNNEL_LOCK_HELD=1`` so its child does not deadlock against the
  parent's fd (flock is fd-scoped).

The holder's identity lives in a SIDECAR file (not the lock file):
``flock(1)`` clients cannot write into the locked file from the shell
wrapper, and reading the lock file would attribute contention to the
last *Python* holder — possibly hours stale.  Writers stamp a UTC time
so readers can judge freshness.
"""

from __future__ import annotations

import time

TUNNEL_LOCK_PATH = "/tmp/tpu_tunnel.lock"
TUNNEL_HOLDER_PATH = "/tmp/tpu_tunnel.holder"

_held_fd = None  # module-held so the fd lives until process exit


def utcnow() -> str:
    """HH:MM:SSZ — the timestamp format of every probe_log entry."""
    return time.strftime("%H:%M:%S", time.gmtime()) + "Z"


_utcnow = utcnow  # internal alias used below


def read_holder() -> str:
    """Best-effort identity of the current (or last) lock holder."""
    try:
        with open(TUNNEL_HOLDER_PATH) as f:
            return f.read().strip() or "?"
    except OSError:
        return "?"


def acquire_tunnel_lock(deadline: float, probe_log: list,
                        label: str = "bench.py") -> bool:
    """Take the tunnel flock, waiting until ``deadline`` (epoch secs).

    Returns True when held (or inherited via ``TPU_TUNNEL_LOCK_HELD``).
    The fd is kept open module-global until process exit, so the tunnel
    stays owned for the whole session.  Contention is appended to
    ``probe_log`` with the holder's identity — the who-owned-the-tunnel
    diagnosis, in the record itself."""
    global _held_fd
    import fcntl
    import os

    if os.environ.get("TPU_TUNNEL_LOCK_HELD") == "1":
        return True
    fd = os.open(TUNNEL_LOCK_PATH, os.O_RDWR | os.O_CREAT, 0o666)
    waited = False
    while True:
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except BlockingIOError:
            if not waited:
                probe_log.append(
                    {"t": _utcnow(), "event": "tunnel_lock_wait",
                     "holder": read_holder()}
                )
                waited = True
            if time.time() >= deadline:
                probe_log.append(
                    {"t": _utcnow(), "event": "tunnel_lock_timeout",
                     "holder": read_holder()}
                )
                os.close(fd)
                return False
            time.sleep(5.0)
            continue
        try:
            with open(TUNNEL_HOLDER_PATH, "w") as f:
                f.write(f"pid={os.getpid()} {label} {_utcnow()}")
        except OSError:
            pass  # attribution is best-effort; the lock itself is held
        if waited:
            probe_log.append(
                {"t": _utcnow(), "event": "tunnel_lock_acquired"}
            )
        _held_fd = fd
        # Children of this process must not re-acquire on a fresh fd —
        # flock is fd-scoped, so they would deadlock against their own
        # parent.  Mirror the shell LOCKRUN wrapper's export.
        os.environ["TPU_TUNNEL_LOCK_HELD"] = "1"
        return True
