"""Tracing / profiling utilities.

The reference's only observability is tqdm postfix text and wall-clock in
committed notebook output (SURVEY.md §5 tracing).  TPU-native replacements:

* ``trace(logdir)`` — context manager around ``jax.profiler`` emitting a
  TensorBoard-loadable trace (XLA op timeline, HBM usage) for any code
  region, e.g. ``with trace('/tmp/tb'): trainer.fit()``.
* ``annotate(name)`` — named region that shows up inside the trace.
* ``StepTimer`` — honest steady-state step timing: async dispatch means
  naive wall-clocks lie (SURVEY.md §7 hard part (e)), so the timer fences
  with ``block_until_ready`` only at measurement boundaries.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Optional

import jax


@contextlib.contextmanager
def trace(logdir: str, create_perfetto_link: bool = False):
    jax.profiler.start_trace(logdir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named trace region (``jax.profiler.TraceAnnotation``)."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Steady-state samples/sec with warmup exclusion and sync fencing.

    Usage::

        timer = StepTimer(warmup=5)
        for batch in loader:
            state, loss, _ = step(state, *batch)
            timer.tick(state, batch_size)
        print(timer.rate())   # samples/sec, compile excluded
    """

    def __init__(self, warmup: int = 5):
        self.warmup = warmup
        self._seen = 0
        self._samples = 0
        self._t0: Optional[float] = None
        self._fence: Any = None

    def tick(self, fence: Any, n_samples: int) -> None:
        self._seen += 1
        self._fence = fence
        if self._seen == self.warmup:
            jax.block_until_ready(fence)
            self._t0 = time.perf_counter()
        elif self._seen > self.warmup:
            self._samples += n_samples

    def rate(self) -> Optional[float]:
        if self._t0 is None or self._samples == 0:
            return None
        jax.block_until_ready(self._fence)
        return self._samples / (time.perf_counter() - self._t0)
