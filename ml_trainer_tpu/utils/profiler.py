"""Tracing / profiling utilities.

The reference's only observability is tqdm postfix text and wall-clock in
committed notebook output (SURVEY.md §5 tracing).  TPU-native replacements:

* ``trace(logdir)`` — context manager around ``jax.profiler`` emitting a
  TensorBoard-loadable trace (XLA op timeline, HBM usage) for any code
  region, e.g. ``with trace('/tmp/tb'): trainer.fit()``.
* ``annotate(name)`` — named region that shows up inside the trace.
* ``StepTimer`` — honest steady-state step timing: async dispatch means
  naive wall-clocks lie (SURVEY.md §7 hard part (e)), so the timer fences
  with a data-dependent value fetch (``force``) only at measurement
  boundaries — see ``force`` for why ``block_until_ready`` is not enough.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Optional

import jax
import numpy as np


def force(fence: Any) -> None:
    """Execution barrier that cannot be faked.

    ``jax.block_until_ready`` is the documented fence, but remote-tunnel
    platforms (the axon TPU plugin here) have been observed returning from
    it before the computation actually ran — which silently inflated every
    throughput number measured through it (observed: ResNet-50 train step
    "7,957 samples/s" via block_until_ready vs 2,076 via a value fetch).
    A device→host read of an output element is data-dependent on the whole
    chain of dispatched executables, so it forces real execution on every
    platform.  Fetches the smallest output leaf (usually a scalar: loss or
    the step counter) to keep the transfer negligible.

    ``block_until_ready`` runs FIRST over the whole tree: on honest
    platforms it is the complete fence (covering leaves from different
    dispatches/devices that the single-leaf fetch would not), and the
    data-dependent fetch then closes the remote-tunnel loophole — both
    guarantees, not one."""
    leaves = [x for x in jax.tree.leaves(fence) if hasattr(x, "shape")]
    if not leaves:
        return
    jax.block_until_ready(leaves)
    smallest = min(leaves, key=lambda x: getattr(x, "size", 1))
    np.asarray(jax.device_get(smallest))


@contextlib.contextmanager
def trace(logdir: str, create_perfetto_link: bool = False):
    jax.profiler.start_trace(logdir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named trace region (``jax.profiler.TraceAnnotation``)."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Steady-state samples/sec with warmup exclusion and sync fencing.

    Usage::

        timer = StepTimer(warmup=5)
        for batch in loader:
            state, loss, _ = step(state, *batch)
            timer.tick(state, batch_size)
        print(timer.rate())   # samples/sec, compile excluded

    ``record_steps=True`` additionally records PER-STEP durations —
    each post-warmup ``tick`` fences (``force``) before reading the
    clock, so every duration covers real execution, and ``p50()`` /
    ``p99()`` report the step-time distribution, not just the mean.
    The per-step fence serializes dispatch against the host (that is
    what makes the numbers honest), so use the default mode when only
    the aggregate rate matters and pipelining should stay live.
    """

    def __init__(self, warmup: int = 5, record_steps: bool = False):
        self.warmup = warmup
        self.record_steps = bool(record_steps)
        self._seen = 0
        self._samples = 0
        self._t0: Optional[float] = None
        self._last: Optional[float] = None
        self._fence: Any = None
        self._durs: list = []

    def tick(self, fence: Any, n_samples: int) -> None:
        self._seen += 1
        self._fence = fence
        if self._seen == self.warmup:
            force(fence)
            self._t0 = time.perf_counter()
            self._last = self._t0
        elif self._seen > self.warmup:
            self._samples += n_samples
            if self.record_steps:
                force(fence)
                now = time.perf_counter()
                self._durs.append(now - self._last)
                self._last = now

    def rate(self) -> Optional[float]:
        if self._t0 is None or self._samples == 0:
            return None
        force(self._fence)
        return self._samples / (time.perf_counter() - self._t0)

    def _percentile(self, q: float) -> Optional[float]:
        if not self._durs:
            return None
        s = sorted(self._durs)
        return s[min(len(s) - 1, int(q * (len(s) - 1) + 0.5))]

    def p50(self) -> Optional[float]:
        """Median fenced step duration (seconds); None unless
        ``record_steps`` collected post-warmup samples."""
        return self._percentile(0.5)

    def p99(self) -> Optional[float]:
        """p99 fenced step duration (seconds); with few samples this is
        the max — still the honest tail proxy."""
        return self._percentile(0.99)
