"""User-extension hooks: custom preprocess + custom loss.

Mirrors ref: src/utils/functions.py:5-17.  The preprocess pipeline keeps the
reference's exact CIFAR-10 recipe and constants — RandomCrop(32, padding=4),
RandomHorizontalFlip, scale-to-[0,1], Normalize(mean=(0.4914, 0.4822,
0.4465), std=(0.2023, 0.1994, 0.2010)) — but as *vectorized host-side batch
transforms* (NHWC) instead of per-sample torchvision ops, so augmentation of
a whole batch is a handful of numpy ops and never starves the TPU.
"""

from __future__ import annotations

import jax.numpy as jnp

from ml_trainer_tpu.data.transforms import (
    Compose,
    Normalize,
    RandomCrop,
    RandomHorizontalFlip,
    ToFloat,
)

CIFAR10_MEAN = (0.4914, 0.4822, 0.4465)
CIFAR10_STD = (0.2023, 0.1994, 0.2010)


def custom_pre_process_function() -> Compose:
    """The reference augmentation pipeline (ref: src/utils/functions.py:5-12),
    batch-vectorized.  ``ToFloat`` plays torchvision ``ToTensor``'s role
    (uint8 [0,255] -> float32 [0,1]) but keeps NHWC layout — channels-last is
    the natural TPU/XLA convolution layout (documented divergence)."""
    return Compose(
        [
            RandomCrop(32, padding=4),
            RandomHorizontalFlip(),
            ToFloat(),
            Normalize(CIFAR10_MEAN, CIFAR10_STD),
        ]
    )


def custom_loss_function(output, target):
    """Mean squared error (ref: src/utils/functions.py:15-17), pure jnp."""
    return jnp.mean((output - target) ** 2)
