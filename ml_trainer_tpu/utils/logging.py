"""Structured logging shim.

The reference logs through ``structlog.get_logger`` (ref: src/trainer.py:19).
structlog is not a hard dependency here: when present it is used directly,
otherwise a stdlib-logging adapter provides the same ``logger.info(msg,
**kv)`` call shape, so the trainer's log sites read identically either way.
"""

from __future__ import annotations

import logging


class _KVLoggerAdapter:
    """Minimal structlog-like facade over ``logging``."""

    def __init__(self, name: str):
        self._log = logging.getLogger(name)
        if not logging.getLogger().handlers and not self._log.handlers:
            handler = logging.StreamHandler()
            handler.setFormatter(logging.Formatter("[%(levelname)s] %(message)s"))
            self._log.addHandler(handler)
            self._log.setLevel(logging.INFO)

    def _fmt(self, event: str, kw) -> str:
        if kw:
            kv = " ".join(f"{k}={v!r}" for k, v in kw.items())
            return f"{event} {kv}"
        return event

    def debug(self, event, **kw):
        self._log.debug(self._fmt(event, kw))

    def info(self, event, **kw):
        self._log.info(self._fmt(event, kw))

    def warning(self, event, **kw):
        self._log.warning(self._fmt(event, kw))

    def error(self, event, **kw):
        self._log.error(self._fmt(event, kw))


def get_logger(name: str = "ml_trainer_tpu"):
    try:
        import structlog

        return structlog.get_logger(name)
    except ImportError:
        return _KVLoggerAdapter(name)
