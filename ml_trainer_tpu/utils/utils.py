"""Notebook-facing utilities: history I/O, model loading, history plotting.

Same public surface as ref: src/utils/utils.py:9-68 so the 01/03 notebook
cell flow keeps working: ``load_history(dir)`` unpickles ``history.pkl``,
``load_model(model, path)`` returns a ready-to-test model object, and
``plot_history(history)`` renders the two-panel loss/metric curves.
"""

from __future__ import annotations

import dataclasses
import os
import pickle
from typing import Any

import numpy as np

from ml_trainer_tpu.checkpoint import load_model_variables, load_torch_checkpoint


class LRUCache:
    """Bounded mapping with least-recently-used eviction.

    Caps the compiled-program caches (``generate._COMPILED``, shared with
    the serving engine's prefill programs): every distinct decode shape
    keeps an XLA executable alive, and a long-lived serving process that
    sees many shapes would otherwise grow without bound.  ``get`` and
    ``__setitem__`` both refresh recency.  Not thread-safe by itself;
    callers that mutate from several threads hold their own lock (the
    serving engine admits from a single worker thread)."""

    def __init__(self, maxsize: int = 64):
        import collections

        if maxsize < 1:
            raise ValueError(f"maxsize must be >= 1, got {maxsize}")
        self.maxsize = maxsize
        self._data = collections.OrderedDict()

    def get(self, key, default=None):
        try:
            self._data.move_to_end(key)
        except KeyError:
            return default
        return self._data[key]

    def __setitem__(self, key, value) -> None:
        self._data[key] = value
        self._data.move_to_end(key)
        while len(self._data) > self.maxsize:
            self._data.popitem(last=False)

    def __contains__(self, key) -> bool:
        return key in self._data

    def __len__(self) -> int:
        return len(self._data)

    def clear(self) -> None:
        self._data.clear()


def load_history(file_dir: str) -> dict:
    """Training history from a directory.  Prefers the ``history.json``
    mirror the Trainer writes next to the pickle (no unpickling, safe
    for offline tooling); falls back to ``history.pkl``
    (ref: src/utils/utils.py:9-12)."""
    json_path = os.path.join(file_dir, "history.json")
    if os.path.exists(json_path):
        import json

        with open(json_path, encoding="utf-8") as fp:
            return json.load(fp)
    path = os.path.join(file_dir, "history.pkl")
    with open(path, "rb") as fp:
        return pickle.load(fp)


@dataclasses.dataclass
class LoadedModel:
    """A module bound to restored variables — what ``load_model`` returns.

    Plays the role of the weight-loaded torch module the reference hands to
    ``trainer.test`` (ref: src/utils/utils.py:15-28, 03 nb cell-7/8); also
    callable directly for ad-hoc inference."""

    module: Any
    variables: dict

    def __call__(self, x, **kwargs):
        return self.module.apply(self.variables, x, **kwargs)


def load_model(model: Any, PATH: str) -> LoadedModel:
    """Load weights from a native ``model.msgpack`` (or its directory) or a
    reference torch ``.pth`` — the latter strips the DDP ``module.`` prefix
    and converts layouts, preserving the reference's checkpoint
    compatibility behaviour (ref: src/utils/utils.py:15-28)."""
    if PATH.endswith((".pth", ".pt")):
        params = load_torch_checkpoint(PATH)
        variables = {"params": params}
    else:
        variables = load_model_variables(PATH)
        if "params" not in variables:
            variables = {"params": variables}
    return LoadedModel(model, variables)


def plot_history(history: dict, show: bool = True):
    """Train-vs-validation curves (ref: src/utils/utils.py:31-68): two panels
    (loss + metric) when a metric was tracked, one otherwise; x-ticks thinned
    past 25 epochs.  Returns the figure; ``show=False`` skips ``plt.show()``
    (headless use/tests)."""
    from matplotlib import pyplot as plt

    x = history["epochs"]
    metric_type = history.get("metric_type")

    def thin_ticks(ax):
        if len(x) > 25:
            ticks = np.arange(0, len(x) + 1, 5)
            ax.set_xticks(ticks)
            ax.set_xticklabels(ticks, rotation=45)
        else:
            ax.set_xticks(x)

    if metric_type is not None:
        fig, (ax_loss, ax_metric) = plt.subplots(2, 1, figsize=(10, 10))
        for ax, train_key, val_key, ylabel, title in (
            (ax_loss, "train_loss", "val_loss", "Loss",
             "Training Loss vs. Validation Loss"),
            (ax_metric, "train_metric", "val_metric", metric_type,
             f"{metric_type} - Training vs. Validation"),
        ):
            ax.plot(x, history[train_key], c="C0", label="train")
            ax.plot(x, history[val_key], c="C1", label="validation")
            thin_ticks(ax)
            ax.set_ylabel(ylabel)
            ax.set_title(title)
            ax.legend()
        ax_loss.set_xlabel("Epochs")
    else:
        fig, ax = plt.subplots(figsize=(10, 5))
        ax.plot(x, history["train_loss"], c="C0", label="train")
        ax.plot(x, history["val_loss"], c="C1", label="validation")
        thin_ticks(ax)
        ax.tick_params(axis="x", rotation=45)
        ax.set_xlabel("Epochs")
        ax.set_ylabel("Loss")
        ax.set_title("Training Loss vs. Validation Loss")
        ax.legend()
    plt.tight_layout()
    if show:
        # Render once and return None — returning the figure too would make
        # a notebook cell ending in plot_history(...) display it twice.
        plt.show()
        return None
    return fig
