from ml_trainer_tpu.utils.functions import (
    custom_loss_function,
    custom_pre_process_function,
)
from ml_trainer_tpu.utils.utils import load_history, load_model, plot_history

__all__ = [
    "custom_loss_function",
    "custom_pre_process_function",
    "load_history",
    "load_model",
    "plot_history",
]
