"""Autoregressive generation with a KV cache — TPU-native decode.

The reference has no generation/serving path at all (SURVEY.md §1: "no
serving layer"); this exists for the GPT-2 north-star family.  Design is
decode-as-one-program: the model runs in flax ``decode`` mode (each block
writes K/V into a fixed-size ``cache`` collection — models/layers.py), the
prompt prefills the cache in ONE batched causal forward (an MXU-friendly
matmul pass, not P single-token steps), and the sampling loop is one
``lax.scan``.  Static shapes throughout (the cache is [B, H, max_len, D]
from step 0), no per-token dispatch, no recompilation as the sequence
grows — the XLA-friendly shape of incremental decoding.  Compiled programs
are cached per (model, shape, temperature-mode), so repeat calls pay
compilation once.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ml_trainer_tpu.utils.utils import LRUCache

# Compiled decode programs keyed by (module, batch, prompt_len,
# max_new_tokens, dtype, greedy, top_k, top_p, eos_token_id,
# pad_token_id) — flax modules are frozen dataclasses, hence hashable
# keys.  The filter/stop values are static (each compiles its own
# program); temperature is traced (does not).  Bounded: every entry pins
# an XLA executable, and a long-lived process seeing many shapes would
# otherwise grow without limit.  The serving engine's bucketed prefill
# programs (serving/engine.py) share this cache under their own key
# prefix, so one knob bounds every compiled decode program in the
# process (env ``ML_TRAINER_TPU_COMPILE_CACHE``).
_COMPILED: LRUCache = LRUCache(
    int(__import__("os").environ.get("ML_TRAINER_TPU_COMPILE_CACHE", "128"))
)


def generate(
    model,
    variables: dict,
    prompt_ids: jax.Array,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
    rng: Optional[jax.Array] = None,
    spec_k: int = 0,
    drafter="ngram",
    draft_variables: Optional[dict] = None,
) -> jax.Array:
    """Generate ``max_new_tokens`` continuations of ``prompt_ids`` [B, P].

    ``model`` is a causal LM from the zoo (e.g. ``get_model('gpt2')``)
    whose module exposes ``decode``/``max_len``; ``variables`` its trained
    ``{'params': ...}``.  ``temperature=0`` is greedy argmax; otherwise
    categorical sampling at ``temperature`` (``rng`` seeds it; temperature
    is traced, so changing it does not recompile), optionally restricted
    to the ``top_k`` most probable tokens and/or the nucleus holding
    ``top_p`` probability mass (both filters compose: top_k first).
    With ``eos_token_id``, a row that emits EOS keeps its static shape
    but pads every later position with ``pad_token_id`` (the decode loop
    still runs — static shapes are the whole design — the finished
    row's draws are just masked out).  Returns
    [B, P + max_new_tokens] token ids.

    ``spec_k > 0`` routes through speculative decoding
    (``ml_trainer_tpu.speculative``): ``drafter`` proposes ``spec_k``
    tokens per step and one verify forward scores them all.  Greedy
    output is byte-identical to the vanilla loop; ``top_k``/``top_p``
    are not supported on this path.
    """
    if spec_k:
        if top_k is not None or top_p is not None:
            raise ValueError(
                "speculative decoding (spec_k > 0) does not support "
                "top_k/top_p filtering — use spec_k=0"
            )
        from ml_trainer_tpu.speculative import speculative_generate

        return speculative_generate(
            model, variables, prompt_ids, max_new_tokens,
            draft_k=spec_k, drafter=drafter,
            draft_variables=draft_variables, temperature=temperature,
            rng=rng, eos_token_id=eos_token_id, pad_token_id=pad_token_id,
        )
    params = variables["params"] if "params" in variables else variables
    b, prompt_len = prompt_ids.shape
    if max_new_tokens < 0:
        raise ValueError(f"max_new_tokens must be >= 0, got {max_new_tokens}")
    if top_k is not None and not 0 < top_k <= model.vocab_size:
        raise ValueError(
            f"top_k must be in [1, vocab_size={model.vocab_size}], got {top_k}"
        )
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if eos_token_id is not None and not 0 <= eos_token_id < model.vocab_size:
        raise ValueError(
            f"eos_token_id must be in [0, vocab_size={model.vocab_size}), "
            f"got {eos_token_id} (a different tokenizer's id would silently "
            "never stop generation)"
        )
    if eos_token_id is not None and not 0 <= pad_token_id < model.vocab_size:
        raise ValueError(
            f"pad_token_id must be in [0, vocab_size={model.vocab_size}), "
            f"got {pad_token_id}"
        )
    if max_new_tokens == 0:
        return prompt_ids
    greedy = temperature == 0.0
    if greedy:
        # Greedy ignores the filters; normalize so the compile cache
        # doesn't build duplicate byte-identical programs per value.
        top_k = None
        top_p = None
    if eos_token_id is None:
        # pad is unused without eos — same normalization rationale.
        pad_token_id = 0
    total = prompt_len + max_new_tokens
    if total > model.max_len:
        raise ValueError(
            f"prompt ({prompt_len}) + new tokens ({max_new_tokens}) exceeds "
            f"the model's max_len ({model.max_len})"
        )
    if rng is None:
        rng = jax.random.PRNGKey(0)

    key = (
        model, b, prompt_len, max_new_tokens, prompt_ids.dtype, greedy,
        top_k, top_p, eos_token_id, pad_token_id,
    )
    run = _COMPILED.get(key)
    if run is None:
        run = _build(
            model, b, prompt_ids.dtype, max_new_tokens, greedy, top_k,
            top_p, eos_token_id, pad_token_id,
        )
        _COMPILED[key] = run
    return run(params, prompt_ids, jnp.asarray(temperature, jnp.float32), rng)


def generate_ragged(
    model,
    variables: dict,
    prompts,
    max_new_tokens: int,
    **kwargs,
) -> list:
    """``generate`` for prompts of UNEQUAL lengths — length-bucketed.

    The decode program requires a static [B, P] prompt block (static
    shapes are what keep the whole loop one compiled program).  Rather
    than pad — left-padding shifts positions and attends pad tokens;
    right-padding would need per-row cache write positions — rows are
    grouped by length and each group runs the ordinary compiled program.
    The compiled-program cache keys on (batch, prompt_len), so each
    group's batch is padded up to a power of two (repeating row 0; the
    padding rows' outputs are dropped) — at most log2 program variants
    per distinct length, regardless of how group sizes vary across
    calls.  ``prompts``: sequence of non-empty 1-D int arrays; returns a
    list of 1-D arrays in the same order, each
    ``len(prompt) + max_new_tokens`` long.  ``kwargs`` pass through to
    ``generate`` (temperature / top_k / top_p / rng); the rng is folded
    with each bucket's length so samples stay independent across buckets.
    """
    prompts = list(prompts)  # tolerate generators: iterated twice below
    by_len: dict = {}
    for i, p in enumerate(prompts):
        p = jnp.asarray(p)
        if p.ndim != 1 or p.shape[0] == 0:
            raise ValueError(
                f"prompts must be non-empty 1-D token arrays; prompt {i} "
                f"has shape {p.shape}"
            )
        by_len.setdefault(p.shape[0], []).append((i, p))
    out: list = [None] * len(prompts)
    rng = kwargs.pop("rng", None)
    if rng is None and kwargs.get("temperature", 0.0) != 0.0:
        # Without this, every bucket would fall through to generate()'s
        # own PRNGKey(0) default and draw with identical key sequences —
        # correlated samples across buckets, contradicting the
        # independence promise above.  Materialize the same default HERE
        # so the per-bucket fold_in below always applies.
        rng = jax.random.PRNGKey(0)
    for length, group in sorted(by_len.items()):
        idx, rows = zip(*group)
        batch = jnp.stack(rows)
        b = batch.shape[0]
        b_pad = 1 << (b - 1).bit_length()
        if b_pad > b:
            batch = jnp.concatenate(
                [batch, jnp.broadcast_to(batch[:1], (b_pad - b, length))]
            )
        group_kwargs = dict(kwargs)
        if rng is not None:
            # Identical keys across buckets would correlate their
            # sampling noise; one fold per bucket restores independence.
            group_kwargs["rng"] = jax.random.fold_in(rng, length)
        done = generate(
            model, variables, batch, max_new_tokens, **group_kwargs
        )
        for j, row in zip(idx, done[:b]):
            out[j] = row
    return out


def beam_search(
    model,
    variables: dict,
    prompt_ids: jax.Array,
    max_new_tokens: int,
    num_beams: int = 4,
) -> jax.Array:
    """Fixed-horizon beam search: the ``num_beams`` highest-scoring
    continuations are kept at every step and the best final sequence is
    returned ([B, P + max_new_tokens]).

    Beams fold into the batch dim of the SAME cached decode program
    ``generate`` uses: one prefill at batch B, the cache tiled to
    B·num_beams, then a ``lax.scan`` whose carry holds (cache, scores,
    sequences) — beam reordering is a gather on the cache's batch axis.
    No EOS semantics (the zoo's synthetic vocabularies have none): all
    beams run the full horizon, so scores compare equal-length sequences
    and no length penalty is needed.
    """
    params = variables["params"] if "params" in variables else variables
    b, prompt_len = prompt_ids.shape
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    if not 0 < num_beams <= model.vocab_size:
        raise ValueError(
            f"num_beams must be in [1, vocab_size={model.vocab_size}], "
            f"got {num_beams}"
        )
    if prompt_len + max_new_tokens > model.max_len:
        raise ValueError(
            f"prompt ({prompt_len}) + new tokens ({max_new_tokens}) exceeds "
            f"the model's max_len ({model.max_len})"
        )
    key = ("beam", model, b, prompt_len, max_new_tokens,
           prompt_ids.dtype, num_beams)
    run = _COMPILED.get(key)
    if run is None:
        run = _build_beam(model, b, prompt_ids.dtype, max_new_tokens,
                          num_beams)
        _COMPILED[key] = run
    return run(params, prompt_ids)


def _cache_shapes(dm, b, dtype):
    """Cache pytree shapes without compute — zeros are exactly the cache's
    initial state (keys/values empty, indices 0)."""
    return jax.eval_shape(
        lambda p: dm.init(
            {"params": p}, jnp.zeros((b, 1), dtype), train=False
        )["cache"],
        jax.random.PRNGKey(0),
    )


def _empty_cache(cache_shapes):
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes)


def _build_beam(model, b, dtype, max_new_tokens, k):
    dm = model.clone(decode=True)
    cache_shapes = _cache_shapes(dm, b, dtype)

    def tile_beams(leaf):
        # [B, ...] -> [B*K, ...]; scalar counters replicate as-is.
        if getattr(leaf, "ndim", 0) == 0:
            return leaf
        return jnp.repeat(leaf, k, axis=0)

    @jax.jit
    def run(params, prompt_ids):
        cache = _empty_cache(cache_shapes)
        logits, mut = dm.apply(
            {"params": params, "cache": cache}, prompt_ids,
            train=False, mutable=["cache"],
        )
        cache = jax.tree.map(tile_beams, mut["cache"])
        logprobs0 = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32))
        # Step 0: all beams share the prefill state, so rank the first
        # tokens directly — top-k over the vocab seeds the beams.
        scores, tok0 = jax.lax.top_k(logprobs0, k)      # [B, K]
        seqs0 = jnp.zeros((b, k, max_new_tokens), dtype)
        seqs0 = seqs0.at[:, :, 0].set(tok0.astype(dtype))

        def step(carry, t):
            cache, scores, seqs = carry
            tok = jax.lax.dynamic_index_in_dim(
                seqs, t - 1, axis=2, keepdims=False
            ).reshape(b * k, 1)
            logits, mut = dm.apply(
                {"params": params, "cache": cache}, tok,
                train=False, mutable=["cache"],
            )
            cache = mut["cache"]
            logprobs = jax.nn.log_softmax(
                logits[:, -1].astype(jnp.float32)
            ).reshape(b, k, -1)
            vocab = logprobs.shape[-1]
            total = scores[:, :, None] + logprobs          # [B, K, V]
            scores, flat_idx = jax.lax.top_k(
                total.reshape(b, k * vocab), k
            )                                              # [B, K]
            beam_idx = flat_idx // vocab                   # [B, K]
            tok_idx = (flat_idx % vocab).astype(dtype)
            # Reorder surviving beams: sequences and the cache batch axis.
            seqs = jnp.take_along_axis(seqs, beam_idx[:, :, None], axis=1)
            seqs = seqs.at[:, :, t].set(tok_idx)
            flat_gather = (
                jnp.arange(b)[:, None] * k + beam_idx
            ).reshape(-1)                                  # [B*K]
            cache = jax.tree.map(
                lambda l: l[flat_gather] if getattr(l, "ndim", 0) else l,
                cache,
            )
            return (cache, scores, seqs), None

        (cache, scores, seqs), _ = jax.lax.scan(
            step, (cache, scores, seqs0), jnp.arange(1, max_new_tokens)
        )
        # top_k returns scores sorted descending, so beam 0 is the winner.
        return jnp.concatenate([prompt_ids, seqs[:, 0]], axis=1)

    return run


def _build(model, b, dtype, max_new_tokens, greedy, top_k=None, top_p=None,
           eos_token_id=None, pad_token_id=0):
    dm = model.clone(decode=True)
    cache_shapes = _cache_shapes(dm, b, dtype)

    def sample(last, temperature, rng, t):
        if greedy:
            return jnp.argmax(last, axis=-1).astype(dtype)
        if top_k is not None:
            # Keep the k most probable logits; the rest cannot be drawn.
            kth = jax.lax.top_k(last, top_k)[0][:, -1:]
            last = jnp.where(last < kth, -jnp.inf, last)
        if top_p is not None:
            # Nucleus: keep the smallest probability mass >= top_p.  Sort
            # descending, find each row's cutoff logit, mask below it —
            # rank-space work stays static-shaped for XLA.  The first
            # token always survives (its EXCLUSIVE cumulative mass is 0),
            # so the distribution cannot empty out.
            sorted_logits = jnp.sort(last, axis=-1)[:, ::-1]
            probs = jax.nn.softmax(sorted_logits / temperature, axis=-1)
            mass_before = jnp.cumsum(probs, axis=-1) - probs
            keep = mass_before < top_p                 # [B, V] in rank space
            # Cutoff = smallest kept logit per row.
            cutoff = jnp.min(
                jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
                keepdims=True,
            )
            last = jnp.where(last < cutoff, -jnp.inf, last)
        return jax.random.categorical(
            jax.random.fold_in(rng, t), last / temperature, axis=-1
        ).astype(dtype)

    def mask_done(tok, done):
        """After a row emits EOS, later positions become pad; returns the
        (masked token, updated done flag) pair."""
        if eos_token_id is None:
            return tok, done
        tok = jnp.where(done[:, None], jnp.asarray(pad_token_id, dtype), tok)
        done = jnp.logical_or(done, tok[:, 0] == eos_token_id)
        return tok, done

    @jax.jit
    def run(params, prompt_ids, temperature, rng):
        cache = _empty_cache(cache_shapes)
        # Prefill: the whole prompt through one causal forward, K/V landing
        # in the cache; its last logits sample the first new token.
        logits, mut = dm.apply(
            {"params": params, "cache": cache}, prompt_ids,
            train=False, mutable=["cache"],
        )
        cache = mut["cache"]
        tok = sample(logits[:, -1], temperature, rng, 0)[:, None]
        tok, done0 = mask_done(tok, jnp.zeros((b,), bool))

        def step(carry, t):
            cache, tok, done = carry
            logits, mut = dm.apply(
                {"params": params, "cache": cache}, tok,
                train=False, mutable=["cache"],
            )
            nxt = sample(logits[:, -1], temperature, rng, t)[:, None]
            nxt, done = mask_done(nxt, done)
            return (mut["cache"], nxt, done), tok

        (_, last_tok, _), toks = jax.lax.scan(
            step, (cache, tok, done0), jnp.arange(1, max_new_tokens)
        )
        # toks holds tokens 0..n-2 (each step emits its INPUT); append the
        # final sampled one.
        new = jnp.concatenate(
            [jnp.moveaxis(toks[:, :, 0], 0, 1), last_tok], axis=1
        ) if max_new_tokens > 1 else last_tok
        return jnp.concatenate([prompt_ids, new], axis=1)

    return run
