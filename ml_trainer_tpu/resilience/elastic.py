"""Elastic training: reshape the mesh around a lost host and keep going.

Preemption used to mean emergency-checkpoint + full restart at the SAME
topology.  This module closes ROADMAP item #1 with the two halves of the
TorchTitan-style drain→reshape→continue behavior (arXiv 2410.06511; the
mesh-reshaping framing is the pjit/TPUv4 paper, arXiv 2204.06514):

* **Topology-flexible restore** — any checkpoint (v2 full-tree, v3
  per-host shards; pure-DP, ZeRO-1, TP/FSDP rule-sharded, pipeline)
  reshards onto a DIFFERENT device count / mesh shape.  The target
  placement is decided here — :func:`remap_state_shardings` carries each
  leaf's PartitionSpec onto the new mesh with the ZeRO-1 shape rule
  re-applied — validated BEFORE any device allocates:
  :func:`precheck_topology` prices the target topology through the
  analytic memory ledger (``plan_train_memory``) and raises a structured
  :class:`TopologyError` when it cannot fit, and
  :func:`validate_reshard` raises a structured :class:`ReshardError`
  naming the offending leaf/dim/axis when a saved shape does not divide
  the new mesh (instead of an XLA reshape traceback).  The placement
  itself is ONE whole-tree ``place_tree`` program (v2) or the v3
  stitch-per-device restore.

* **Elastic controller** — configuration for the Trainer's in-flight
  reshape: ``Trainer(elastic=ElasticConfig(n_hosts=N))`` treats the
  local mesh as N simulated hosts (the chaos-harness analog of a TPU
  pod's host groups; ``data`` is the outermost mesh axis, so each host
  owns a contiguous block of data replicas).  On a ``host_kill`` /
  ``host_hang`` fault (resilience/faults.py) or a straggler verdict
  from ``telemetry/cluster.py``, the trainer drains the in-flight step,
  writes the emergency checkpoint, drops the lost host's devices from
  the mesh, re-places the state (one ``place_tree``), rescales global
  batch / LR per :attr:`ElasticConfig.batch_policy`, and continues the
  SAME ``fit()`` call — recorded in ``history['reshapes']``, a flight
  ``reshape`` event, the goodput ``reshape`` bucket and
  ``run_report.json``.

Multi-process pods cannot reshape in place (the process set is fixed at
``jax.distributed.initialize``); there the same faults drive the
drain→checkpoint→restart-at-new-topology path, and the topology-flexible
restore is what lets the restarted job continue (tests/test_elastic.py,
scripts/elastic_smoke.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# ------------------------------------------------------- structured errors
class ReshardError(ValueError):
    """A saved/live array cannot be placed on the target mesh: some
    dimension does not divide the mesh axes its PartitionSpec names.
    Carries the coordinates a post-mortem needs instead of an XLA
    reshape traceback: the leaf path, the offending dim/size, the axis
    and its size, and the source vs target topologies."""

    def __init__(self, *, leaf: str, dim: int, size: int, axes,
                 axis_size: int,
                 source_topology: Optional[dict] = None,
                 target_topology: Optional[dict] = None,
                 reason: Optional[str] = None):
        self.leaf = leaf
        self.dim = int(dim)
        self.size = int(size)
        self.axes = tuple(axes) if isinstance(axes, (tuple, list)) else (axes,)
        self.axis_size = int(axis_size)
        self.source_topology = source_topology
        self.target_topology = target_topology
        axis_str = "x".join(str(a) for a in self.axes)
        msg = (
            f"cannot reshard leaf {leaf!r}: dim {dim} of size {size} does "
            f"not divide mesh axis {axis_str!r} of size {axis_size}"
        )
        if source_topology:
            msg += f" (saved on mesh {_topo_str(source_topology)}"
            msg += (
                f", restoring onto {_topo_str(target_topology)})"
                if target_topology else ")"
            )
        elif target_topology:
            msg += f" (target mesh {_topo_str(target_topology)})"
        if reason:
            msg += f"; {reason}"
        super().__init__(msg)


class TopologyError(ValueError):
    """The target topology cannot run this config: the analytic memory
    ledger predicts the per-device peak exceeds chip capacity (checked
    BEFORE any device allocates), or the mesh cannot be built around
    the lost host at all.  ``verdict`` carries the planner's numbers."""

    def __init__(self, message: str, verdict: Optional[dict] = None):
        self.verdict = verdict or {}
        super().__init__(message)


def _topo_str(topo: Optional[dict]) -> str:
    if not topo:
        return "<unknown>"
    axes = topo.get("axes", topo)
    if isinstance(axes, dict):
        return "{" + ", ".join(f"{a}: {s}" for a, s in axes.items()) + "}"
    return str(axes)


# -------------------------------------------------------------- topologies
def mesh_topology(mesh: Mesh) -> Dict[str, Any]:
    """The JSON-able topology record of a mesh — what checkpoint
    manifests and ``PREEMPTED.json`` carry so a restore knows the shape
    of the world that wrote them."""
    return {
        "axes": {str(a): int(s) for a, s in mesh.shape.items()},
        "device_count": int(mesh.size),
        "process_count": int(jax.process_count()),
    }


def state_topology(tree) -> Optional[Dict[str, Any]]:
    """Topology of the first mesh-placed leaf in ``tree`` (None when no
    leaf carries a ``NamedSharding`` — host-only states)."""
    for leaf in jax.tree.leaves(tree):
        sh = getattr(leaf, "sharding", None)
        if isinstance(sh, NamedSharding):
            return mesh_topology(sh.mesh)
    return None


def host_groups(devices: Sequence, n_hosts: int) -> List[list]:
    """Split a mesh's flat device list into ``n_hosts`` equal contiguous
    groups — the simulated-host decomposition.  ``data`` is the
    outermost mesh axis (parallel/mesh.py AXIS_ORDER), so each group is
    a contiguous block of data replicas and dropping one leaves a valid
    (smaller) mesh grid."""
    devices = list(devices)
    if n_hosts < 2:
        raise ValueError(f"n_hosts must be >= 2, got {n_hosts}")
    if len(devices) % n_hosts:
        raise ValueError(
            f"{len(devices)} devices do not split into {n_hosts} equal "
            "simulated hosts"
        )
    per = len(devices) // n_hosts
    return [devices[h * per:(h + 1) * per] for h in range(n_hosts)]


def shrink_mesh_shape(old_shape: Dict[str, int], old_n: int,
                      new_n: int) -> Dict[str, int]:
    """The mesh shape after losing ``old_n - new_n`` devices: the
    ``data`` axis absorbs the whole shrink (model axes — tensor / fsdp /
    stage — partition the MODEL; shrinking them would change the
    program, not just the replica count).  Raises :class:`TopologyError`
    when the surviving devices cannot keep the model axes whole."""
    old_shape = {str(a): int(s) for a, s in old_shape.items()}
    model = {a: s for a, s in old_shape.items() if a != "data"}
    model_n = int(np.prod(list(model.values()), initial=1))
    if new_n < 1 or new_n % model_n:
        raise TopologyError(
            f"cannot reshape {old_n} -> {new_n} devices: the surviving "
            f"device count must keep the model axes {model} whole "
            f"(multiple of {model_n})",
            verdict={"old_devices": old_n, "new_devices": new_n,
                     "model_axes": model},
        )
    new_data = new_n // model_n
    out = dict(old_shape)
    out["data"] = new_data
    return out


# ------------------------------------------------- reshard spec remapping
# The per-leaf spec carry-over lives with the other placement rules in
# parallel/sharding.py; re-exported here as part of the elastic API.
from ml_trainer_tpu.parallel.sharding import respec_sharding  # noqa: E402


def _spec_axis_size(entry, mesh: Mesh) -> int:
    axes = entry if isinstance(entry, tuple) else (entry,)
    return int(np.prod([mesh.shape[a] for a in axes], initial=1))


def remap_state_shardings(shardings, state, new_mesh: Mesh):
    """Per-leaf target shardings for a whole state tree on a new mesh.

    Each leaf keeps its spec (re-bound to the new mesh); leaves carrying
    the ZeRO-1 signature — dim 0 partitioned over the data-like axes,
    all other dims replicated — fall back to replicated when dim 0 no
    longer divides the new axis size, exactly the shape rule
    ``zero1_opt_shardings`` would have applied on the new mesh.  Leaves
    sharded by MODEL rules (tensor/fsdp/stage dims) never silently
    replicate — an indivisible model shard is a :class:`ReshardError`
    the caller surfaces via :func:`validate_reshard`."""
    data_like = ("data",)

    def remap(sharding, leaf):
        if not isinstance(sharding, NamedSharding):
            return sharding
        new = respec_sharding(sharding, new_mesh)
        spec = tuple(new.spec)
        shape = tuple(getattr(leaf, "shape", ()))
        if (
            shape
            and len(spec) >= 1
            and spec[0] is not None
            and all(e is None for e in spec[1:])
            and all(
                a in data_like
                for a in (spec[0] if isinstance(spec[0], tuple) else (spec[0],))
            )
        ):
            n = _spec_axis_size(spec[0], new_mesh)
            if n > 1 and shape[0] % n:
                return NamedSharding(new_mesh, P())  # zero1 shape rule
        return new

    return jax.tree.map(remap, shardings, state)


def validate_reshard(state, shardings, *,
                     source_topology: Optional[dict] = None) -> None:
    """Check that every leaf's shape divides its target sharding's mesh
    axes — the divisibility contract an elastic restore must satisfy —
    and raise a structured :class:`ReshardError` naming the first
    offender.  Pure metadata: nothing allocates.  ``state`` may hold
    real arrays, numpy, or ``ShapeDtypeStruct`` leaves."""
    leaves = jax.tree_util.tree_flatten_with_path(state)[0]
    sh_leaves = jax.tree.leaves(shardings)
    if len(leaves) != len(sh_leaves):
        raise ValueError(
            f"state/shardings tree mismatch: {len(leaves)} vs "
            f"{len(sh_leaves)} leaves"
        )
    from ml_trainer_tpu.parallel.sharding import path_str

    for (path, leaf), sharding in zip(leaves, sh_leaves):
        if not isinstance(sharding, NamedSharding):
            continue
        shape = tuple(getattr(leaf, "shape", ()))
        target = mesh_topology(sharding.mesh)
        for dim, entry in enumerate(tuple(sharding.spec)[:len(shape)]):
            if entry is None:
                continue
            n = _spec_axis_size(entry, sharding.mesh)
            if n > 1 and shape[dim] % n:
                raise ReshardError(
                    leaf=path_str(path), dim=dim, size=shape[dim],
                    axes=entry, axis_size=n,
                    source_topology=source_topology,
                    target_topology=target,
                )


# -------------------------------------------------- pre-allocation checks
def precheck_topology(model, batch_shape: Sequence[int],
                      mesh_shape: Optional[Dict[str, int]] = None, *,
                      optimizer: str = "adamw",
                      capacity_bytes: Optional[float] = None,
                      margin: float = 0.95,
                      **plan_kwargs) -> dict:
    """Price a target topology through the analytic memory ledger BEFORE
    any device allocates (``plan_train_memory`` is ``jax.eval_shape``
    only) and raise :class:`TopologyError` when the predicted per-device
    peak exceeds ``margin`` × chip capacity.  Returns the planner's
    verdict dict on success — the elastic controller calls this with the
    post-reshape mesh shape, so a reshape that cannot fit fails with the
    planner's numbers instead of a device OOM mid-recovery."""
    from ml_trainer_tpu.telemetry.memory import fit_verdict, plan_train_memory

    ledger = plan_train_memory(
        model, tuple(batch_shape), optimizer=optimizer,
        mesh_shape=mesh_shape, **plan_kwargs,
    )
    verdict = fit_verdict(
        ledger.peak_bytes(), capacity_bytes=capacity_bytes, margin=margin
    )
    verdict["mesh_shape"] = dict(mesh_shape or {})
    if verdict["verdict"] == "oom" or (
        capacity_bytes is not None and verdict["utilization"] > 1.0
    ):
        raise TopologyError(
            f"target topology {_topo_str({'axes': mesh_shape or {}})} "
            f"cannot fit: predicted per-device peak "
            f"{verdict['peak_bytes']:,} bytes exceeds capacity "
            f"{verdict['capacity_bytes']:,} "
            f"(utilization {verdict['utilization']:.2f})",
            verdict=verdict,
        )
    return verdict


# ------------------------------------------------- topology-flexible load
def elastic_restore(path: str, state_template, shardings, *,
                    validate: bool = True):
    """Restore a checkpoint onto a (possibly different) target topology.

    * v3 per-host shard directories stitch each device's slice directly
      onto ``shardings`` (the saved piece grid and the target shard grid
      need not match);
    * v2 full-tree directories (and legacy v1 pickles) restore to host
      arrays and place the WHOLE tree in one ``place_tree`` program.

    ``validate=True`` (default) runs :func:`validate_reshard` against
    the template shapes first, so an incompatible topology fails with a
    structured :class:`ReshardError` before any device allocates.
    Returns ``(state, history, epoch)`` like ``restore_checkpoint``."""
    from ml_trainer_tpu import checkpoint as ckpt
    from ml_trainer_tpu.parallel.sharding import place_tree

    source = ckpt.checkpoint_topology(path)
    if validate:
        validate_reshard(
            state_template, shardings,
            source_topology=source,
        )
    if ckpt.checkpoint_format(path) == 3:
        return ckpt.restore_checkpoint(path, state_template, shardings)
    state, history, epoch = ckpt.restore_checkpoint(
        path, jax.device_get(state_template)
    )
    return place_tree(state, shardings), history, epoch


# ----------------------------------------------------- controller config
@dataclass
class ElasticConfig:
    """Knobs of the Trainer's in-flight mesh reshape.

    ``n_hosts``
        Simulated host count the local mesh decomposes into (each host =
        one contiguous block of data replicas).  The ``data`` axis must
        be divisible by it.

    ``batch_policy``
        ``'global'`` (default): the global batch is PRESERVED across a
        reshape — each survivor takes a larger per-device share, the
        math (and therefore the trajectory) is unchanged, and the
        mid-epoch cursor carries over directly.  ``'per_device'``: the
        per-device batch is preserved — the global batch shrinks by the
        survivor ratio and the LR rescales by the same factor (the
        linear scaling rule), trading trajectory identity for constant
        per-device memory/latency.

    ``straggler_reshape_factor``
        When set, a straggler verdict from ``telemetry/cluster.py``
        whose factor reaches this bound requests a reshape around the
        straggling host (None = stragglers only alarm).

    ``max_reshapes``
        Hard cap on in-flight reshapes per ``fit()`` (a flapping
        cluster must not shrink itself to nothing).

    ``capacity_bytes`` / ``margin``
        Overrides for the pre-reshape :func:`precheck_topology` fit
        check (None = the chip HBM table)."""

    n_hosts: int = 2
    batch_policy: str = "global"
    straggler_reshape_factor: Optional[float] = None
    max_reshapes: int = 8
    capacity_bytes: Optional[float] = None
    margin: float = 0.95
    min_hosts: int = 1

    def __post_init__(self):
        if self.n_hosts < 2:
            raise ValueError(
                f"elastic n_hosts must be >= 2, got {self.n_hosts}"
            )
        if self.batch_policy not in ("global", "per_device"):
            raise ValueError(
                "elastic batch_policy must be 'global' | 'per_device', "
                f"got {self.batch_policy!r}"
            )
        if (
            self.straggler_reshape_factor is not None
            and self.straggler_reshape_factor <= 1.0
        ):
            raise ValueError(
                "straggler_reshape_factor must be > 1, got "
                f"{self.straggler_reshape_factor}"
            )
        if self.max_reshapes < 1:
            raise ValueError(
                f"max_reshapes must be >= 1, got {self.max_reshapes}"
            )
        if not (1 <= self.min_hosts < self.n_hosts):
            raise ValueError(
                f"min_hosts must be in [1, n_hosts), got {self.min_hosts}"
            )


def resolve_elastic(value) -> Optional[ElasticConfig]:
    """``Trainer(elastic=...)`` resolution: None stays off, an int is
    the simulated host count, a config passes through."""
    if value is None or value is False:
        return None
    if isinstance(value, ElasticConfig):
        return value
    if isinstance(value, bool):  # True without a host count is ambiguous
        raise ValueError(
            "elastic=True is ambiguous; pass the simulated host count "
            "(elastic=2) or an ElasticConfig"
        )
    if isinstance(value, int):
        return ElasticConfig(n_hosts=value)
    raise TypeError(
        f"elastic must be None, an int host count, or ElasticConfig; "
        f"got {type(value).__name__}"
    )


@dataclass
class ReshapeRequest:
    """One pending drain→reshape request (trigger + the lost host)."""

    trigger: str  # 'host_kill' | 'host_hang' | 'straggler'
    lost_host: int
    step: Optional[int] = None
    detail: dict = field(default_factory=dict)
