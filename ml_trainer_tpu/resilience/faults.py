"""Deterministic fault injection — the chaos harness behind docs/resilience.md.

A ``FaultPlan`` is a parsed list of faults, each a (kind, trigger,
params) triple, injected through hooks threaded into the layers a real
TPU job fails in:

* ``nan_grad``      — trainer: the batch at the matching step is turned
                      into NaNs so the compiled step produces non-finite
                      loss/grads (exercises the on-device all-finite
                      guard and rollback).
* ``preempt``       — trainer: the matching step requests preemption —
                      the exact path a SIGTERM takes (finish the
                      in-flight step, emergency checkpoint, clean exit).
* ``ckpt_truncate`` — checkpoint writer: the checkpoint of the matching
                      epoch has one leaf file truncated AFTER the commit
                      rename, simulating storage corruption that only
                      CRC verification can catch.
* ``decode_wedge``  — serving engine: the matching decode step blocks
                      (bounded by ``secs``) as a wedged device program
                      would; the serving watchdog must fail the clients.
* ``decode_error``  — NativeLoader: the matching epoch reports an
                      injected decode failure through the loader's
                      corrupt-sample accounting path.
* ``host_kill``     — elastic chaos: the named host dies at the
                      matching step.  In a multi-process cluster the
                      matching worker hard-exits mid-loop (the SIGKILL'd
                      pod host — no emergency checkpoint); in the
                      single-process simulated cluster the elastic
                      controller drains, checkpoints, and reshapes the
                      mesh around the lost host (resilience/elastic.py).
* ``host_hang``     — elastic chaos: the named host stalls.  A matching
                      multi-process worker sleeps ``secs`` (a real
                      straggler for telemetry/cluster.py to catch); the
                      single-process simulation treats it as a
                      straggler verdict and reshapes.
* ``replica_kill``  — serving chaos: the router's health poller kills
                      the replica at fleet index ``host`` at the
                      matching busy poll — watchdog-style death, the
                      drain-and-redistribute path under real load.
                      Against a multi-process fleet replica
                      (serving/fleet.py) the kill is a REAL ``SIGKILL``
                      of the worker process: streams sever mid-socket
                      with no goodbye, and recovery is the same
                      redistribute-from-committed-prefix path the
                      in-process simulation exercises.
* ``replica_slow``  — serving chaos: the matching replica's serve loop
                      latches a slow-down window of ``secs`` seconds
                      (every loop iteration sleeps) once it is busy —
                      a throttled/straggling replica the hedging and
                      breaker machinery must route around.
* ``healthz_flap``  — serving chaos: ONE health poll against the
                      matching replica looks dropped (transient
                      timeout); the router's flap damping must absorb
                      it without a spurious drain-and-redistribute.
* ``migration_corrupt`` — serving chaos: the next KV migration payload
                      through the router has one bit flipped in
                      flight; the CRC32 verify on import must refuse
                      it and the router retry on a fallback candidate.

Spec syntax (also accepted via the ``ML_TRAINER_TPU_FAULTS`` env var)::

    nan_grad@step=12;ckpt_truncate@epoch=1;preempt@step=40;decode_wedge@step=5
    host_kill@step=9,host=1
    replica_kill@step=3,host=2;replica_slow@step=1,host=0,secs=8

Entries are ``kind@key=value[,key=value...]`` separated by ``;``.
Trigger keys: ``step`` (1-based train/decode step) or ``epoch``.
Params: ``count`` (consecutive steps to fire on, default 1), ``secs``
(wedge/hang/slow hold bound, default 300), and ``host`` (the host index
a ``host_kill``/``host_hang`` names — or the replica fleet index for
the serving kinds; default 0).  Serving hooks pass their own replica
index to ``fire(..., host=)``, so a fault naming ``host=2`` only fires
in replica 2's hook (host-filtered matching); the trainer's host_kill
flow keeps its original semantics — the hook omits ``host=`` and
checks ``fault.host`` itself.

Every hook is a no-op when no plan is active, and every fault fires a
bounded number of times — injection is reproducible, never ambient.
Tests install plans programmatically (``install``/``injected``); the env
var serves CLI smoke runs.
"""

from __future__ import annotations

import contextlib
import os
import threading
from dataclasses import dataclass
from typing import List, Optional

ENV_VAR = "ML_TRAINER_TPU_FAULTS"

KINDS = ("nan_grad", "preempt", "ckpt_truncate", "decode_wedge",
         "decode_error", "host_kill", "host_hang",
         "replica_kill", "replica_slow", "healthz_flap",
         "migration_corrupt")

# Kinds whose ``host`` param names a target (pod host index, or the
# serving fleet's replica index for the serving chaos kinds).
HOSTED_KINDS = ("host_kill", "host_hang", "replica_kill", "replica_slow",
                "healthz_flap", "migration_corrupt")


@dataclass
class Fault:
    """One injectable fault: fires when its trigger matches, at most
    ``count`` times (consecutive steps for step triggers)."""

    kind: str
    step: Optional[int] = None
    epoch: Optional[int] = None
    count: int = 1
    secs: float = 300.0
    host: int = 0  # the host index a host_kill/host_hang names
    fired: int = 0

    def matches(self, step: Optional[int], epoch: Optional[int],
                host: Optional[int] = None) -> bool:
        if self.fired >= self.count:
            return False
        if host is not None and self.host != host:
            # Host-filtered matching: a serving hook names its own
            # replica index, so a fault targeting host=2 never consumes
            # a firing in replica 0's hook.
            return False
        if self.step is not None:
            return step is not None and (
                self.step <= step < self.step + self.count
            )
        if self.epoch is not None:
            return epoch is not None and epoch == self.epoch
        return True  # unconditional: fires `count` times, then stops

    def spec(self) -> str:
        parts = []
        if self.step is not None:
            parts.append(f"step={self.step}")
        if self.epoch is not None:
            parts.append(f"epoch={self.epoch}")
        if self.count != 1:
            parts.append(f"count={self.count}")
        if self.kind in HOSTED_KINDS:
            parts.append(f"host={self.host}")
        return self.kind + ("@" + ",".join(parts) if parts else "")


class FaultPlan:
    """A parsed fault list plus the wedge-release latch (thread-safe).

    ``fire(kind, step=..., epoch=...)`` is the single hook entry point:
    it returns the matching :class:`Fault` (marking one firing consumed)
    or ``None``.  Hooks call it with whatever trigger coordinates they
    know; a fault conditioned on a key the hook did not pass never
    fires.
    """

    def __init__(self, faults: List[Fault]):
        self.faults = list(faults)
        self._lock = threading.Lock()
        self._wedge_release = threading.Event()

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        faults = []
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            kind, _, args = entry.partition("@")
            kind = kind.strip()
            if kind not in KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} in {entry!r}; "
                    f"expected one of {sorted(KINDS)}"
                )
            kwargs = {}
            for pair in filter(None, (p.strip() for p in args.split(","))):
                key, sep, value = pair.partition("=")
                if not sep:
                    raise ValueError(
                        f"malformed fault trigger {pair!r} in {entry!r} "
                        "(expected key=value)"
                    )
                key = key.strip()
                if key not in ("step", "epoch", "count", "secs", "host"):
                    raise ValueError(
                        f"unknown fault key {key!r} in {entry!r}; "
                        "expected step|epoch|count|secs|host"
                    )
                kwargs[key] = float(value) if key == "secs" else int(value)
            if "host" in kwargs and kind not in HOSTED_KINDS:
                raise ValueError(
                    f"'host' only applies to host/replica-targeted "
                    f"faults {sorted(HOSTED_KINDS)} (got it on {kind!r} "
                    f"in {entry!r})"
                )
            faults.append(Fault(kind=kind, **kwargs))
        return cls(faults)

    def fire(self, kind: str, *, step: Optional[int] = None,
             epoch: Optional[int] = None,
             host: Optional[int] = None) -> Optional[Fault]:
        with self._lock:
            fired = None
            for fault in self.faults:
                if fault.kind == kind and fault.matches(step, epoch, host):
                    fault.fired += 1
                    fired = fault
                    break
        if fired is not None:
            # The injection itself goes on the flight-recorder timeline:
            # a chaos dump then shows the fault next to the step records
            # it poisoned (telemetry/flight.py).
            from ml_trainer_tpu.telemetry.flight import get_recorder

            get_recorder().record(
                "fault_injected", fault=fired.spec(), step=step, epoch=epoch
            )
        return fired

    # -- wedge latch (decode_wedge) -------------------------------------
    def hold_wedge(self, fault: Fault) -> None:
        """Block as a wedged decode step would, until ``release_wedge``
        (or the fault's ``secs`` bound — injected faults must never hang
        a process forever)."""
        self._wedge_release.wait(timeout=fault.secs)

    def release_wedge(self) -> None:
        self._wedge_release.set()

    def remaining(self) -> List[Fault]:
        with self._lock:
            return [f for f in self.faults if f.fired < f.count]

    def __repr__(self) -> str:
        return f"FaultPlan({'; '.join(f.spec() for f in self.faults)})"


# -- process-wide active plan -------------------------------------------
# Programmatic installs win over the env var; the env spec is parsed
# lazily and re-parsed only when its value changes (tests mutate it).
_installed: Optional[FaultPlan] = None
_env_cache: tuple = ("", None)
_state_lock = threading.Lock()


def install(plan: FaultPlan) -> FaultPlan:
    global _installed
    with _state_lock:
        _installed = plan
    return plan


def uninstall() -> None:
    global _installed
    with _state_lock:
        _installed = None


def active_plan() -> Optional[FaultPlan]:
    """The installed plan, else one parsed from ``ML_TRAINER_TPU_FAULTS``
    (cached per env value), else None.  Hook sites call this on every
    potential injection point — it is cheap by construction."""
    global _env_cache
    with _state_lock:
        if _installed is not None:
            return _installed
        spec = os.environ.get(ENV_VAR, "")
        if not spec:
            return None
        if _env_cache[0] != spec:
            _env_cache = (spec, FaultPlan.parse(spec))
        return _env_cache[1]


@contextlib.contextmanager
def injected(spec_or_plan):
    """Context manager: install a plan (or parse a spec string) for the
    duration of the block."""
    plan = (
        spec_or_plan
        if isinstance(spec_or_plan, FaultPlan)
        else FaultPlan.parse(spec_or_plan)
    )
    install(plan)
    try:
        yield plan
    finally:
        uninstall()
