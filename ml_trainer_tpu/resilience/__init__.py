"""Resilience layer: deterministic fault injection plus the hardening it
proves out (docs/resilience.md).

The injection harness lives in :mod:`ml_trainer_tpu.resilience.faults`;
the defenses live where the failures do — the trainer's on-device
all-finite guard, step-granular checkpoints and preemption handling
(``trainer.py``), checkpoint CRC verification and corrupt-dir quarantine
(``checkpoint/checkpoint.py``), and the serving watchdog/drain
(``serving/api.py``).  Elastic training — topology-flexible restore and
the drain→reshape→continue controller — lives in
:mod:`ml_trainer_tpu.resilience.elastic`.
"""

from ml_trainer_tpu.resilience.elastic import (
    ElasticConfig,
    ReshardError,
    TopologyError,
    elastic_restore,
    precheck_topology,
    validate_reshard,
)
from ml_trainer_tpu.resilience.faults import (
    ENV_VAR,
    Fault,
    FaultPlan,
    active_plan,
    injected,
    install,
    uninstall,
)

__all__ = [
    "ENV_VAR",
    "ElasticConfig",
    "Fault",
    "FaultPlan",
    "ReshardError",
    "TopologyError",
    "active_plan",
    "elastic_restore",
    "injected",
    "install",
    "precheck_topology",
    "uninstall",
    "validate_reshard",
]
