"""LoRA: train low-rank adapters in-stack, serve thousands per base.

One base checkpoint cannot serve millions of users: production traffic
is thousands of fine-tuned variants (the Gemma-on-TPU serving paper's
per-chip-cost framing, arXiv 2605.25645), and TorchTitan's
composable-feature thesis (arXiv 2410.06511) says the train side should
be a Trainer knob, not a fork.  This module is the shared half of both:

* :class:`LoraConfig` — the ``Trainer(lora=...)`` knob: rank, alpha,
  targeted Dense projections (models/layers.py ``LORA_TARGETS``).  The
  Trainer clones the model with the matching ``lora_*`` fields (A/B
  become ordinary flax params, B zero-init so step 0 IS the base
  model), freezes the base through an ``optax.multi_transform`` mask —
  frozen leaves carry NO optimizer state, so optimizer memory divides
  by the frozen fraction (verified by the memory ledger) — and trains
  only A/B.
* **Artifact format** — :func:`export_lora_artifact` /
  :func:`load_lora_artifact`: one ``.npz`` holding every target's A/B
  plus a JSON meta record (rank, alpha, targets, base fingerprint).
  This is the unit the serving engine hot-loads into its adapter pool
  (serving/adapter_pool.py) under live traffic.
* :func:`lora_param_labels` / :func:`split_lora_params` — the
  ``_lora_A``/``_lora_B`` naming convention is the single source of
  truth for "what is adapter, what is base" on both sides.

Host-side file I/O and tree walks only — no compiled-program surface.
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import zlib
from typing import Dict, Optional, Tuple

import jax
import numpy as np

from ml_trainer_tpu.models.layers import LORA_TARGETS

# Param-name suffixes marking adapter leaves (models/layers.py
# lora_delta creates them as ``<target>_lora_A`` / ``<target>_lora_B``).
_LORA_MARKERS = ("_lora_A", "_lora_B")

ARTIFACT_VERSION = 1


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    """The ``Trainer(lora=...)`` knob.

    ``rank``: adapter rank r (A: [in, r], B: [r, out]).  ``alpha``:
    scale numerator — the delta is ``alpha/rank · xAB`` (the standard
    LoRA parameterization, so quality is rank-robust).  ``targets``:
    which Dense projections carry adapters (default: attention qkv +
    output proj)."""

    rank: int = 8
    alpha: float = 16.0
    targets: Tuple[str, ...] = ("qkv", "proj")

    def __post_init__(self):
        if self.rank < 1:
            raise ValueError(f"lora rank must be >= 1, got {self.rank}")
        if self.alpha <= 0:
            raise ValueError(f"lora alpha must be > 0, got {self.alpha}")
        targets = tuple(self.targets)
        if not targets:
            raise ValueError("lora targets must name >= 1 projection")
        bad = [t for t in targets if t not in LORA_TARGETS]
        if bad:
            raise ValueError(
                f"unknown lora target(s) {bad}; choose from {LORA_TARGETS}"
            )
        object.__setattr__(self, "targets", targets)

    def scale(self) -> float:
        return float(self.alpha) / float(self.rank)


def is_lora_path(path_name: str) -> bool:
    """True when a param path names an adapter leaf (A or B)."""
    return any(m in path_name for m in _LORA_MARKERS)


def _path_str(path) -> str:
    return "/".join(str(getattr(k, "key", k)) for k in path)


def lora_param_labels(params) -> Dict:
    """A tree matching ``params`` labeling each leaf ``'lora'`` or
    ``'frozen'`` — the ``optax.multi_transform`` mask the Trainer
    freezes the base with (frozen leaves get ``set_to_zero`` updates
    AND no optimizer state)."""
    flat = jax.tree_util.tree_flatten_with_path(params)
    return jax.tree_util.tree_unflatten(
        flat[1],
        ["lora" if is_lora_path(_path_str(p)) else "frozen"
         for p, _ in flat[0]],
    )


def split_lora_params(params) -> Tuple[Dict[str, np.ndarray], int, int]:
    """Collect the adapter leaves out of a trained param tree.

    Returns ``(leaves, n_lora, n_frozen)`` where ``leaves`` maps the
    flat ``a/b/c`` param path to a host array."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    leaves: Dict[str, np.ndarray] = {}
    n_frozen = 0
    for path, leaf in flat:
        name = _path_str(path)
        if is_lora_path(name):
            leaves[name] = np.asarray(leaf)
        else:
            n_frozen += 1
    return leaves, len(leaves), n_frozen


def strip_lora_params(params):
    """The BASE-only param tree (every ``*_lora_*`` leaf removed) — what
    a serving engine's pool-mode model expects as ``params`` (serve-mode
    adapters live in the "lora" collection, not in params)."""
    def walk(node):
        if isinstance(node, dict):
            return {
                k: walk(v) for k, v in node.items()
                if not (isinstance(k, str) and is_lora_path(k))
            }
        if hasattr(node, "items"):  # FrozenDict
            return {
                k: walk(v) for k, v in node.items()
                if not (isinstance(k, str) and is_lora_path(k))
            }
        return node

    return walk(params)


def base_fingerprint(params) -> str:
    """Cheap stable fingerprint of the FROZEN base weights: CRC32 over
    each non-LoRA leaf's bytes, combined in path order.  Rides the
    artifact meta so a server can warn when an adapter trained against
    a different base checkpoint is loaded."""
    crc = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        name = _path_str(path)
        if is_lora_path(name):
            continue
        arr = np.ascontiguousarray(np.asarray(leaf))
        crc = zlib.crc32(name.encode() + arr.tobytes(), crc)
    return f"{crc:#010x}"


def export_lora_artifact(params, config: LoraConfig, path: str,
                         name: Optional[str] = None) -> dict:
    """Write one adapter artifact (``.npz``): every ``*_lora_A``/``_B``
    leaf from ``params`` plus a JSON meta record.  Returns the meta.
    The serving pool consumes this via :func:`load_lora_artifact` —
    the hot-load unit."""
    leaves, n_lora, _ = split_lora_params(params)
    if not n_lora:
        raise ValueError(
            "params carry no *_lora_A/*_lora_B leaves — was the model "
            "built with Trainer(lora=LoraConfig(...))?"
        )
    meta = {
        "version": ARTIFACT_VERSION,
        "name": name or os.path.splitext(os.path.basename(path))[0],
        "rank": int(config.rank),
        "alpha": float(config.alpha),
        "targets": list(config.targets),
        "base_fingerprint": base_fingerprint(params),
        "n_leaves": n_lora,
    }
    buf = io.BytesIO()
    np.savez(
        buf,
        meta=np.frombuffer(json.dumps(meta).encode(), np.uint8),
        **{f"leaf::{k}": v for k, v in sorted(leaves.items())},
    )
    tmp = path + ".tmp"
    with open(tmp, "wb") as fp:
        fp.write(buf.getvalue())
    os.replace(tmp, path)
    return meta


def load_lora_artifact(source) -> Tuple[dict, Dict[str, np.ndarray]]:
    """Read an adapter artifact — a path, bytes, or an already-loaded
    ``(meta, leaves)`` pair (passed through).  Returns
    ``(meta, {param_path: array})``."""
    if isinstance(source, tuple) and len(source) == 2:
        return source
    if isinstance(source, (bytes, bytearray)):
        data = np.load(io.BytesIO(bytes(source)), allow_pickle=False)
    else:
        data = np.load(source, allow_pickle=False)
    with data as z:
        meta = json.loads(bytes(z["meta"].tobytes()).decode())
        leaves = {
            k[len("leaf::"):]: np.asarray(z[k])
            for k in z.files if k.startswith("leaf::")
        }
    if int(meta.get("version", 0)) != ARTIFACT_VERSION:
        raise ValueError(
            f"unsupported lora artifact version {meta.get('version')!r} "
            f"(this build reads {ARTIFACT_VERSION})"
        )
    if len(leaves) != int(meta.get("n_leaves", -1)):
        raise ValueError(
            f"lora artifact corrupt: {len(leaves)} leaves, meta says "
            f"{meta.get('n_leaves')}"
        )
    return meta, leaves
