"""GPT-2 124M causal LM — the pretrain north-star config
(BASELINE.json configs[4]: grad-accum + checkpoint save/restore) and the
framework's flagship long-context model.

Pre-LN decoder stack with causal attention through ops.attention (so the
Pallas flash kernel and ring sequence parallelism apply), learned position
embeddings, weight-tied LM head (logits = h @ tok_embedᵀ — halves embedding
memory and is the published GPT-2 arrangement).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from ml_trainer_tpu.models.layers import TransformerBlock
from ml_trainer_tpu.models.registry import register_model


class GPT2(nn.Module):
    vocab_size: int = 50257
    max_len: int = 1024
    embed_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.float32
    attention_impl: str = "auto"
    mesh: object = None  # jax Mesh; needed for attention_impl='ring'
    moe_experts: int = 0  # >0: MoE feed-forward in every block (EP axis)
    remat: bool = False  # jax.checkpoint each block: O(depth) -> O(1)
    # layer activations live in HBM during backward (long-context lever)

    @nn.compact
    def __call__(self, input_ids, train: bool = False):
        b, s = input_ids.shape
        tok_embed = nn.Embed(self.vocab_size, self.embed_dim, name="tok_embed")
        x = tok_embed(input_ids)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.01),
            (1, self.max_len, self.embed_dim),
        )
        x = (x + pos[:, :s]).astype(self.dtype)
        if self.dropout_rate:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        # remat: recompute each block's activations in the backward pass
        # instead of keeping them in HBM (jax.checkpoint; train arg static).
        Block = (
            nn.remat(TransformerBlock, static_argnums=(3,))
            if self.remat
            else TransformerBlock
        )
        for i in range(self.depth):
            x = Block(
                num_heads=self.num_heads, mlp_dim=4 * self.embed_dim,
                causal=True, dropout_rate=self.dropout_rate, dtype=self.dtype,
                attention_impl=self.attention_impl, mesh=self.mesh,
                moe_experts=self.moe_experts, name=f"block{i}",
            )(x, None, train)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_final")(x)
        # Tied LM head: reuse the token embedding matrix.
        logits = x.astype(jnp.float32) @ tok_embed.embedding.T.astype(jnp.float32)
        return logits


@register_model("gpt2")
def gpt2(**kw) -> GPT2:
    """GPT-2 124M: 12 layers, 768 wide, 12 heads, 50257 vocab."""
    return GPT2(**kw)


@register_model("gpt2_tiny")
def gpt2_tiny(**kw) -> GPT2:
    """Small GPT-2 for tests: 2 layers, 128 wide, 1k vocab."""
    kw.setdefault("vocab_size", 1024)
    kw.setdefault("embed_dim", 128)
    kw.setdefault("depth", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("max_len", 256)
    return GPT2(**kw)


@register_model("gpt2_moe_tiny")
def gpt2_moe_tiny(**kw) -> GPT2:
    """gpt2_tiny with a 4-expert MoE feed-forward — the expert-parallel
    test/demo config (mesh axis ``expert``, rules_for(..., 'ep'))."""
    kw.setdefault("moe_experts", 4)
    return gpt2_tiny(**kw)
