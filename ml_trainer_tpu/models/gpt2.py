"""GPT-2 124M causal LM — the pretrain north-star config
(BASELINE.json configs[4]: grad-accum + checkpoint save/restore) and the
framework's flagship long-context model.

Pre-LN decoder stack with causal attention through ops.attention (so the
Pallas flash kernel and ring sequence parallelism apply), learned position
embeddings, weight-tied LM head (logits = h @ tok_embedᵀ — halves embedding
memory and is the published GPT-2 arrangement).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from ml_trainer_tpu.models.layers import TransformerBlock, remat_block
from ml_trainer_tpu.models.registry import register_model


def _embed_input(mdl: nn.Module, input_ids, pos_start=None):
    """Shared non-trunk front end for the GPT-2 variants: token embedding +
    learned positions (params ``tok_embed``/``pos_embed`` on ``mdl`` — one
    definition so GPT2, GPT2Pipelined and the decode path cannot drift
    apart).  ``pos_start`` (traced scalar) offsets the position slice for
    KV-cached decoding.  Returns the embedded activations and the embed
    module for head tying."""
    import jax as _jax

    s = input_ids.shape[1]
    tok_embed = nn.Embed(mdl.vocab_size, mdl.embed_dim, name="tok_embed")
    x = tok_embed(input_ids)
    pos = mdl.param(
        "pos_embed", nn.initializers.normal(0.01),
        (1, mdl.max_len, mdl.embed_dim),
    )
    if pos_start is None:
        pos_slice = pos[:, :s]
    elif getattr(pos_start, "ndim", 0) == 1:
        # Per-row positions (serving slots / speculative verify windows:
        # each batch row sits at its own sequence position).  ``s == 1``
        # is the decode step; ``s > 1`` gathers a length-s position
        # window per row (clipped at max_len — out-of-range rows are
        # inactive slots whose outputs nobody reads).
        pos_slice = jnp.take(
            pos[0],
            pos_start[:, None] + jnp.arange(s)[None, :],
            axis=0,
            mode="clip",
        )
    else:
        pos_slice = _jax.lax.dynamic_slice(
            pos, (0, pos_start, 0), (1, s, mdl.embed_dim)
        )
    return (x + pos_slice).astype(mdl.dtype), tok_embed


def _tied_head(mdl: nn.Module, x, tok_embed, targets=None):
    """Shared back end: final LayerNorm + weight-tied LM head (logits =
    h @ tok_embedᵀ — halves embedding memory, the published GPT-2
    arrangement).  With ``targets`` (and ``mdl.loss_chunk`` set) it
    returns the chunked LM loss instead — one LayerNorm definition for
    both paths, so the 'ln_final' parameter cannot diverge."""
    x = nn.LayerNorm(dtype=mdl.dtype, name="ln_final")(x)
    if targets is not None:
        # Model-computed loss: the [B, S, V] logits tensor (the memory
        # hot spot — ~0.8 GB for the 124M config at bs=8) is never
        # materialized; see ops.losses.chunked_lm_cross_entropy.  The
        # Trainer drives this path for models that accept ``targets``
        # (metric must be None — there are no logits to score).
        if not getattr(mdl, "loss_chunk", 0):
            raise ValueError(
                "targets requires loss_chunk > 0 (set loss_chunk to a "
                "divisor of the sequence length to enable the chunked "
                "LM loss)"
            )
        from ml_trainer_tpu.ops.losses import chunked_lm_cross_entropy

        return chunked_lm_cross_entropy(
            x, tok_embed.embedding, targets, mdl.loss_chunk
        )
    return x.astype(jnp.float32) @ tok_embed.embedding.T.astype(jnp.float32)


class GPT2(nn.Module):
    vocab_size: int = 50257
    max_len: int = 1024
    embed_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.float32
    attention_impl: str = "auto"
    mesh: object = None  # jax Mesh; needed for attention_impl='ring'
    moe_experts: int = 0  # >0: MoE feed-forward in every block (EP axis)
    moe_top_k: int = 1    # experts per token (1 = Switch, 2 = GShard)
    remat: bool = False  # jax.checkpoint each block: O(depth) -> O(1)
    # layer activations live in HBM during backward (long-context lever)
    remat_policy: str = "none"  # what remat may KEEP: 'none' (recompute
    # everything), 'dots' (keep matmul outputs — recompute only the cheap
    # elementwise chain: ~2x less recompute FLOPs for ~the matmul
    # activations' memory back).  Only read when remat=True.
    decode: bool = False  # KV-cached single-token inference (generate())
    loss_chunk: int = 0  # >0: with targets, chunked LM loss (see __call__)
    # Paged KV serving (serving/kv_pool.py): the decode cache becomes a
    # shared page pool + per-row page tables (models/layers.py).
    kv_page_size: int = 0
    kv_pages: int = 0
    # Pallas kernel knobs (ops/kernels/): fused paged-attention decode
    # and int8 weight-quantized projections.  Both resolve to lax
    # references off-TPU, so byte-identity holds on CPU; the engine owns
    # the refusal rules (paged_kernel needs kv_page_size > 0, quant_int8
    # excludes spec_k / adapters).
    paged_kernel: bool = False
    quant_int8: bool = False
    # LoRA (models/layers.py lora_delta; docs/serving.md "Batched LoRA
    # adapters"): rank > 0 adds low-rank deltas on ``lora_targets``.
    # ``lora_slots == 0`` is TRAIN mode (one trainable adapter as
    # params); ``lora_slots > 0`` is SERVE mode — the adapter pool
    # stacks live in the "lora" collection and each batch row gathers
    # its own adapter through the per-row ``adapter_idx`` vector the
    # serving engine supplies in that collection.
    lora_rank: int = 0
    lora_alpha: float = 1.0
    lora_slots: int = 0
    lora_targets: tuple = ()

    @nn.compact
    def __call__(self, input_ids, train: bool = False, targets=None):
        if self.decode:
            # Positions come from a cached counter so the whole decode
            # loop (prefill at S=P, then S=1 steps) runs under one
            # compiled program.
            pos_idx = self.variable(
                "cache", "pos_index", lambda: jnp.zeros((), jnp.int32)
            )
            x, tok_embed = _embed_input(
                self, input_ids, pos_start=pos_idx.value
            )
            pos_idx.value = pos_idx.value + input_ids.shape[1]
        else:
            x, tok_embed = _embed_input(self, input_ids)
        if self.dropout_rate:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        adapter_idx = None
        if self.lora_rank and self.lora_slots:
            # Serving pool mode: the per-row adapter index rides the
            # "lora" collection next to the pool stacks (the engine
            # supplies both as ordinary program inputs — swapping which
            # adapter a row reads never recompiles).
            adapter_idx = self.variable(
                "lora", "adapter_idx",
                lambda: jnp.zeros((input_ids.shape[0],), jnp.int32),
            ).value
        # remat: recompute each block's activations in the backward pass
        # instead of keeping them in HBM (jax.checkpoint; train arg static).
        Block = remat_block(self.remat, self.remat_policy)
        for i in range(self.depth):
            block = Block(
                num_heads=self.num_heads, mlp_dim=4 * self.embed_dim,
                causal=True, dropout_rate=self.dropout_rate, dtype=self.dtype,
                attention_impl=self.attention_impl, mesh=self.mesh,
                moe_experts=self.moe_experts, moe_top_k=self.moe_top_k,
                decode=self.decode,
                decode_max_len=self.max_len if self.decode else 0,
                kv_page_size=self.kv_page_size, kv_pages=self.kv_pages,
                paged_kernel=self.paged_kernel, quant_int8=self.quant_int8,
                lora_rank=self.lora_rank, lora_alpha=self.lora_alpha,
                lora_slots=self.lora_slots, lora_targets=self.lora_targets,
                name=f"block{i}",
            )
            if self.lora_rank:
                x = block(x, None, train, None, adapter_idx)
            else:
                x = block(x, None, train)
        return _tied_head(self, x, tok_embed, targets)


@register_model("gpt2")
def gpt2(**kw) -> GPT2:
    """GPT-2 124M: 12 layers, 768 wide, 12 heads, 50257 vocab."""
    return GPT2(**kw)


@register_model("gpt2_medium")
def gpt2_medium(**kw) -> GPT2:
    """GPT-2 355M: 24 layers, 1024 wide, 16 heads."""
    kw.setdefault("embed_dim", 1024)
    kw.setdefault("depth", 24)
    kw.setdefault("num_heads", 16)
    return GPT2(**kw)


@register_model("gpt2_large")
def gpt2_large(**kw) -> GPT2:
    """GPT-2 774M: 36 layers, 1280 wide, 20 heads."""
    kw.setdefault("embed_dim", 1280)
    kw.setdefault("depth", 36)
    kw.setdefault("num_heads", 20)
    return GPT2(**kw)


@register_model("gpt2_tiny")
def gpt2_tiny(**kw) -> GPT2:
    """Small GPT-2 for tests: 2 layers, 128 wide, 1k vocab."""
    kw.setdefault("vocab_size", 1024)
    kw.setdefault("embed_dim", 128)
    kw.setdefault("depth", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("max_len", 256)
    return GPT2(**kw)


@register_model("gpt2_moe_tiny")
def gpt2_moe_tiny(**kw) -> GPT2:
    """gpt2_tiny with a 4-expert MoE feed-forward — the expert-parallel
    test/demo config (mesh axis ``expert``, rules_for(..., 'ep'))."""
    kw.setdefault("moe_experts", 4)
    return gpt2_tiny(**kw)


@register_model("gpt2_mini")
def gpt2_mini(**kw) -> GPT2:
    """Mid-size GPT-2 (≈29M params): 4 layers, 512 wide, 8k vocab.

    The speculative-decoding bench/serving demo target: large enough
    that a decode forward is weight-streaming-bound — a K+1-token verify
    window costs ~2x a single-token step, not K+1x — which is the regime
    where drafting pays (bench.py --spec)."""
    kw.setdefault("vocab_size", 8192)
    kw.setdefault("embed_dim", 512)
    kw.setdefault("depth", 4)
    kw.setdefault("num_heads", 8)
    kw.setdefault("max_len", 512)
    return GPT2(**kw)


@register_model("gpt2_nano")
def gpt2_nano(**kw) -> GPT2:
    """Draft-model config paired with ``gpt2_mini``: 1 layer, 128 wide,
    the SAME 8k vocabulary (speculative acceptance compares token ids, so
    vocab identity is the compatibility contract — models/registry.py
    records the pairing)."""
    kw.setdefault("vocab_size", 8192)
    kw.setdefault("embed_dim", 128)
    kw.setdefault("depth", 1)
    kw.setdefault("num_heads", 4)
    kw.setdefault("max_len", 512)
    return GPT2(**kw)


class GPT2Pipelined(nn.Module):
    """Pipeline-parallel GPT-2 trainable through the Trainer.

    The TPU-idiomatic stage split: the REPEATED, equal-width transformer
    blocks form the pipeline trunk — their params live stacked
    ``[n_stages, ...]`` and shard ``P('stage', ...)`` (PP_RULES), executing
    through ``parallel.pipeline.pipeline_apply`` (activations hop stage →
    stage over ICI ppermute inside one lax.scan).  The unequal-width ends —
    token/position embedding and the tied LM head — run OUTSIDE the
    pipeline, replicated: an SPMD pipeline needs shape-homogeneous stages,
    so heterogeneous ends ride outside the trunk (the arrangement used by
    production TPU pipelining; the reference has no PP at all, SURVEY.md
    §2C).

    With ``mesh=None`` the SAME stacked params fold serially via
    ``lax.scan`` — one param structure for both execution modes, which is
    what lets tests assert pipelined == serial trajectories exactly.
    The trunk is dropout-free (GPipe microbatches would need per-stage RNG
    plumbing; the reference parity configs train without dropout anyway).
    """

    vocab_size: int = 50257
    max_len: int = 1024
    embed_dim: int = 768
    n_stages: int = 4
    num_heads: int = 12
    dtype: jnp.dtype = jnp.float32
    mesh: object = None  # jax Mesh with a live 'stage' axis -> pipelined
    n_microbatches: int = 0  # 0 -> one microbatch per stage
    remat: bool = False  # recompute stage bodies in backward (O(1) ticks
    # of activation memory instead of O(S+M-1); math unchanged)
    schedule: str = "gpipe"  # pipeline schedule (parallel.pipeline
    # SCHEDULES: gpipe | 1f1b | interleaved | zb); same math, different
    # WHERE/WHEN — the Trainer's `pipeline_schedule=` knob clones this.
    n_virtual: int = 1  # interleaved only: virtual stages per device;
    # the mesh's stage axis then spans n_stages // n_virtual devices.

    @nn.compact
    def __call__(self, input_ids, train: bool = False):
        import jax

        from ml_trainer_tpu.parallel.pipeline import pipeline_apply

        x, tok_embed = _embed_input(self, input_ids)

        # One block TEMPLATE; its params are created stacked [n_stages, ...]
        # so they shard over the stage mesh axis as a single pytree.
        block = TransformerBlock(
            num_heads=self.num_heads, mlp_dim=4 * self.embed_dim,
            causal=True, dtype=self.dtype,
        )

        def stacked_init(rng):
            dummy = jnp.zeros((1, 1, self.embed_dim), self.dtype)

            def one(r):
                return block.init({"params": r}, dummy, None, False)["params"]

            return jax.vmap(one)(jax.random.split(rng, self.n_stages))

        blocks = self.param("blocks", stacked_init)

        def stage_fn(p, mb):
            return block.apply({"params": p}, mb, None, False)

        if self.mesh is not None and "stage" in getattr(
            self.mesh, "axis_names", ()
        ):
            x = pipeline_apply(
                stage_fn, blocks, x, self.mesh,
                n_microbatches=self.n_microbatches or None,
                remat=self.remat,
                schedule=self.schedule,
                n_virtual=self.n_virtual,
            )
        else:
            body = jax.checkpoint(stage_fn) if self.remat else stage_fn
            x, _ = jax.lax.scan(
                lambda carry, p: (body(p, carry), None), x, blocks
            )
        return _tied_head(self, x, tok_embed)


@register_model("gpt2_pipe")
def gpt2_pipe(**kw) -> GPT2Pipelined:
    """GPT-2 124M with the 12 blocks as pipeline stages."""
    kw.setdefault("n_stages", 12)
    return GPT2Pipelined(**kw)


@register_model("gpt2_pipe_tiny")
def gpt2_pipe_tiny(**kw) -> GPT2Pipelined:
    """Small pipelined GPT-2 for tests: 4 stages of 64-wide blocks."""
    kw.setdefault("vocab_size", 256)
    kw.setdefault("embed_dim", 64)
    kw.setdefault("n_stages", 4)
    kw.setdefault("num_heads", 2)
    kw.setdefault("max_len", 128)
    return GPT2Pipelined(**kw)
