"""MLModel — the reference's LeNet-style CIFAR-10 CNN, TPU-native.

Architecture parity with ref: src/model.py:7-24:
Conv(3→6, 5×5, VALID) → ReLU → MaxPool(2,2) → Conv(6→16, 5×5, VALID) → ReLU
→ MaxPool(2,2) → flatten(400) → Dense(120) → ReLU → Dense(84) → ReLU →
Dense(10).

TPU-native choices: NHWC layout (XLA's preferred conv layout on TPU; the
reference is NCHW) and flatten in H,W,C order — the torch-checkpoint
importer permutes fc1 accordingly (see checkpoint.torch_import)."""

from __future__ import annotations

import flax.linen as nn

from ml_trainer_tpu.models.registry import register_model


@register_model("mlmodel")
class MLModel(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(nn.Conv(6, (5, 5), padding="VALID", name="conv1")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(16, (5, 5), padding="VALID", name="conv2")(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120, name="fc1")(x))
        x = nn.relu(nn.Dense(84, name="fc2")(x))
        x = nn.Dense(self.num_classes, name="fc3")(x)
        return x
