"""Model zoo.

``MLModel`` is the parity model (the reference's LeNet-style CIFAR-10 CNN,
ref: src/model.py:7-24); the rest are the north-star families from
BASELINE.json: ResNet-18/50, ViT-B/16, BERT-base, GPT-2-124M — all flax
modules designed for TPU (NHWC convs, bf16-friendly, attention through
ops/attention so the Pallas flash kernel and ring sequence parallelism plug
in uniformly).
"""

from ml_trainer_tpu.models.mlmodel import MLModel
from ml_trainer_tpu.models.registry import get_model, register_model, MODELS

__all__ = ["MLModel", "get_model", "register_model", "MODELS"]
