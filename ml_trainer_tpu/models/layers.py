"""Shared transformer building blocks (flax), TPU-first.

No analog in the reference (its only model is a 62K-param CNN,
ref: src/model.py) — these exist for the north-star families
(BASELINE.json configs[2..4]).  Design notes:

* all attention flows through ``ops.attention`` so the Pallas flash kernel,
  the XLA path and (via ``parallel.ring``) ring sequence-parallel attention
  are interchangeable behind one module;
* ``dtype`` threads bf16 activation compute through every block (params stay
  f32 — the standard TPU mixed-precision recipe for the ViT config);
* weight layouts keep the contraction dim leading/trailing such that the
  tensor-parallel PartitionSpecs in ``parallel.tp_rules`` shard cleanly
  (qkv/mlp-in column-parallel, proj/mlp-out row-parallel).
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from ml_trainer_tpu.ops.attention import attention


class MultiHeadAttention(nn.Module):
    """Self-attention over [B, S, E] with heads split for ops.attention."""

    num_heads: int
    head_dim: Optional[int] = None
    causal: bool = False
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.float32
    attention_impl: str = "auto"
    mesh: Optional[object] = None  # jax Mesh, required for 'ring'

    @nn.compact
    def __call__(self, x, mask=None, train: bool = False, kv_lens=None):
        embed = x.shape[-1]
        head_dim = self.head_dim or embed // self.num_heads
        inner = self.num_heads * head_dim
        # Fused QKV projection: one [E, 3·inner] matmul keeps the MXU busy
        # and gives tensor parallelism a single column-sharded kernel.
        qkv = nn.Dense(3 * inner, dtype=self.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):  # [B, S, inner] -> [B, H, S, D]
            b, s, _ = t.shape
            return t.reshape(b, s, self.num_heads, head_dim).transpose(0, 2, 1, 3)

        out = attention(
            heads(q), heads(k), heads(v),
            causal=self.causal, mask=mask, kv_lens=kv_lens,
            implementation=self.attention_impl,
            mesh=self.mesh,
        )
        b, h, s, d = out.shape
        out = out.transpose(0, 2, 1, 3).reshape(b, s, h * d)
        out = nn.Dense(embed, dtype=self.dtype, name="proj")(out)
        if self.dropout_rate:
            out = nn.Dropout(self.dropout_rate, deterministic=not train)(out)
        return out


class MLP(nn.Module):
    """Transformer feed-forward block."""

    hidden_dim: int
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.float32
    activation: Callable = nn.gelu

    @nn.compact
    def __call__(self, x, train: bool = False):
        embed = x.shape[-1]
        x = nn.Dense(self.hidden_dim, dtype=self.dtype, name="fc_in")(x)
        x = self.activation(x)
        x = nn.Dense(embed, dtype=self.dtype, name="fc_out")(x)
        if self.dropout_rate:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return x


class TransformerBlock(nn.Module):
    """Pre-LN transformer block (the GPT-2/ViT arrangement; BERT uses
    post-LN via the ``post_norm`` flag)."""

    num_heads: int
    mlp_dim: int
    causal: bool = False
    dropout_rate: float = 0.0
    post_norm: bool = False
    dtype: jnp.dtype = jnp.float32
    attention_impl: str = "auto"
    mesh: Optional[object] = None
    moe_experts: int = 0  # >0: MoE feed-forward (expert parallelism)

    @nn.compact
    def __call__(self, x, mask=None, train: bool = False, kv_lens=None):
        attn = lambda y: MultiHeadAttention(
            self.num_heads, causal=self.causal, dropout_rate=self.dropout_rate,
            dtype=self.dtype, attention_impl=self.attention_impl,
            mesh=self.mesh, name="attn",
        )(y, mask=mask, train=train, kv_lens=kv_lens)
        if self.moe_experts:
            from ml_trainer_tpu.models.moe import MoEMLP

            mlp = lambda y: MoEMLP(
                self.moe_experts, self.mlp_dim, dtype=self.dtype, name="mlp",
            )(y, train=train)
        else:
            mlp = lambda y: MLP(
                self.mlp_dim, dropout_rate=self.dropout_rate, dtype=self.dtype,
                name="mlp",
            )(y, train=train)
        ln1 = nn.LayerNorm(dtype=self.dtype, name="ln1")
        ln2 = nn.LayerNorm(dtype=self.dtype, name="ln2")
        if self.post_norm:  # BERT-style
            x = ln1(x + attn(x))
            x = ln2(x + mlp(x))
        else:  # GPT-2/ViT-style
            x = x + attn(ln1(x))
            x = x + mlp(ln2(x))
        return x
