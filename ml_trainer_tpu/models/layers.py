"""Shared transformer building blocks (flax), TPU-first.

No analog in the reference (its only model is a 62K-param CNN,
ref: src/model.py) — these exist for the north-star families
(BASELINE.json configs[2..4]).  Design notes:

* all attention flows through ``ops.attention`` so the Pallas flash kernel,
  the XLA path and (via ``parallel.ring``) ring sequence-parallel attention
  are interchangeable behind one module;
* ``dtype`` threads bf16 activation compute through every block (params stay
  f32 — the standard TPU mixed-precision recipe for the ViT config);
* weight layouts keep the contraction dim leading/trailing such that the
  tensor-parallel PartitionSpecs in ``parallel.tp_rules`` shard cleanly
  (qkv/mlp-in column-parallel, proj/mlp-out row-parallel).
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ml_trainer_tpu.ops.attention import attention

# Dense targets a LoRA adapter may attach to (docs/serving.md "Batched
# LoRA adapters"): the attention and MLP projections.  Embeddings and
# the tied LM head stay base-only by design.
LORA_TARGETS = ("qkv", "proj", "fc_in", "fc_out")


def lora_delta(mdl: nn.Module, name: str, x, features: int,
               adapter_idx=None):
    """Low-rank delta for Dense target ``name``: added AFTER the base
    projection, so base param paths (and the base program when LoRA is
    off) are untouched.

    Two modes, selected by the owning module's static fields:

    * **Train** (``lora_rank > 0``, ``lora_slots == 0``): one trainable
      adapter — params ``<name>_lora_A`` (init N(0, 0.01²)) and
      ``<name>_lora_B`` (init zeros, so step 0 is the base model
      exactly), delta ``(x @ A @ B) · alpha/rank``.  The base kernel
      stays frozen by the Trainer's optimizer mask, not here.
    * **Serve** (``lora_slots > 0``): a POOL of adapters lives in the
      ``"lora"`` collection — stacks ``A [S, in, rank]`` /
      ``B [S, rank, out]`` owned and uploaded by the serving engine
      (serving/adapter_pool.py) — and every batch row gathers ITS OWN
      adapter by index: ``(x @ A[idx]) @ B[idx]``.  Slot 0 is the trash
      adapter (all-zero), so rows with no adapter compute an exact-zero
      delta and stay bit-identical to the base model.  The alpha/rank
      scale is folded into ``B`` at upload time, so mixed-rank
      adapters (zero-padded to the pool's rank bucket) share this ONE
      program — adapter swap/hot-load never recompiles.
    """
    rank = int(mdl.lora_rank)
    slots = int(mdl.lora_slots)
    in_dim = x.shape[-1]
    if slots:
        A = mdl.variable(
            "lora", f"{name}_lora_A",
            lambda: jnp.zeros((slots, in_dim, rank), mdl.dtype),
        ).value
        B = mdl.variable(
            "lora", f"{name}_lora_B",
            lambda: jnp.zeros((slots, rank, features), mdl.dtype),
        ).value
        if adapter_idx is None:
            # Init trace (no engine-supplied index yet): every row reads
            # the trash adapter — the zero delta.
            adapter_idx = jnp.zeros((x.shape[0],), jnp.int32)
        a = jnp.take(A, adapter_idx, axis=0)         # [B, in, rank]
        b = jnp.take(B, adapter_idx, axis=0)         # [B, rank, out]
        xa = jnp.einsum("bsi,bir->bsr", x.astype(a.dtype), a)
        return jnp.einsum("bsr,bro->bso", xa, b)
    A = mdl.param(
        f"{name}_lora_A", nn.initializers.normal(0.01), (in_dim, rank)
    )
    B = mdl.param(
        f"{name}_lora_B", nn.initializers.zeros, (rank, features)
    )
    scale = float(mdl.lora_alpha) / rank
    x = x.astype(mdl.dtype)
    return (x @ A.astype(mdl.dtype) @ B.astype(mdl.dtype)) * scale


def _quant_dense(mdl: nn.Module, name: str, x, features: int):
    """Int8 weight-quantized replacement for Dense target ``name``.

    Reads ``<name>_w`` (int8 [K, N]) / ``<name>_scale`` (f32 [N]) /
    ``<name>_b`` (f32 [N]) from the ``"quant"`` collection — built
    host-side by ``ops.kernels.quantize_tree`` from the fp32 params, so
    param paths and checkpoints never change and only the decode model
    clone flips the knob.  The fp32 ``kernel``/``bias`` params go unread
    by this program (flax apply tolerates unused collections entries).
    """
    from ml_trainer_tpu.ops.kernels.int8_matmul import int8_matmul

    in_dim = x.shape[-1]
    w = mdl.variable(
        "quant", f"{name}_w",
        lambda: jnp.zeros((in_dim, features), jnp.int8),
    ).value
    s = mdl.variable(
        "quant", f"{name}_scale",
        lambda: jnp.ones((features,), jnp.float32),
    ).value
    b = mdl.variable(
        "quant", f"{name}_b",
        lambda: jnp.zeros((features,), jnp.float32),
    ).value
    y = int8_matmul(x.astype(mdl.dtype), w, s)
    return y + b.astype(y.dtype)


class MultiHeadAttention(nn.Module):
    """Self-attention over [B, S, E] with heads split for ops.attention.

    ``decode=True`` switches to single-token autoregressive mode (flax's
    standard cache pattern): each call consumes x of sequence length 1,
    appends its key/value into a ``cache`` collection ([B, H, L, D] ring
    written at ``cache_index``) and attends the query against every cached
    position so far.  The decode loop then runs as one ``lax.scan`` with
    the cache as carry — no recompilation per step, no growing shapes.
    ``decode_max_len`` fixes the cache length L (static shapes for XLA).
    """

    num_heads: int
    head_dim: Optional[int] = None
    causal: bool = False
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.float32
    attention_impl: str = "auto"
    mesh: Optional[object] = None  # jax Mesh, required for 'ring'
    decode: bool = False
    decode_max_len: int = 0
    # Paged KV cache (serving/kv_pool.py): > 0 switches the decode cache
    # from per-row contiguous [B, H, L, D] blocks to a SHARED pool of
    # fixed-size pages [kv_pages, H, kv_page_size, D] addressed through a
    # per-row page table — rows own pages, not max_len regions, so pool
    # memory tracks live tokens and identical prefixes can share pages.
    kv_page_size: int = 0
    kv_pages: int = 0
    # Pallas paged-attention decode (ops/kernels/paged_attention.py):
    # fuse the page-table gather into the attention kernel on the S == 1
    # step.  'auto' dispatch resolves to the lax reference off-TPU —
    # bitwise the gather path below — so flipping this knob never
    # changes bytes on CPU.
    paged_kernel: bool = False
    # Int8 weight-quantized decode (ops/kernels/int8_matmul.py): the
    # qkv/proj projections read int8 weights + per-column scales from
    # the "quant" collection instead of the fp32 params.
    quant_int8: bool = False
    # LoRA (see lora_delta): rank > 0 adds low-rank deltas on the
    # targeted projections — trainable single-adapter params when
    # lora_slots == 0, the serving engine's per-row-indexed adapter pool
    # when lora_slots > 0.
    lora_rank: int = 0
    lora_alpha: float = 1.0
    lora_slots: int = 0
    lora_targets: tuple = ()

    @nn.compact
    def __call__(self, x, mask=None, train: bool = False, kv_lens=None,
                 adapter_idx=None):
        embed = x.shape[-1]
        head_dim = self.head_dim or embed // self.num_heads
        inner = self.num_heads * head_dim
        # Fused QKV projection: one [E, 3·inner] matmul keeps the MXU busy
        # and gives tensor parallelism a single column-sharded kernel.
        if self.quant_int8:
            qkv = _quant_dense(self, "qkv", x, 3 * inner)
        else:
            qkv = nn.Dense(3 * inner, dtype=self.dtype, name="qkv")(x)
        if self.lora_rank and "qkv" in self.lora_targets:
            qkv = qkv + lora_delta(self, "qkv", x, 3 * inner, adapter_idx)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):  # [B, S, inner] -> [B, H, S, D]
            b, s, _ = t.shape
            return t.reshape(b, s, self.num_heads, head_dim).transpose(0, 2, 1, 3)

        if self.decode:
            if mask is not None or kv_lens is not None:
                raise ValueError(
                    "decode mode attends the cached prefix; mask/kv_lens "
                    "are not supported (an error rather than a silent drop)"
                )
            out = self._decode_step(heads(q), heads(k), heads(v))
        else:
            out = attention(
                heads(q), heads(k), heads(v),
                causal=self.causal, mask=mask, kv_lens=kv_lens,
                implementation=self.attention_impl,
                mesh=self.mesh,
            )
        b, h, s, d = out.shape
        out = out.transpose(0, 2, 1, 3).reshape(b, s, h * d)
        attn_out = out
        if self.quant_int8:
            out = _quant_dense(self, "proj", out, embed)
        else:
            out = nn.Dense(embed, dtype=self.dtype, name="proj")(out)
        if self.lora_rank and "proj" in self.lora_targets:
            out = out + lora_delta(self, "proj", attn_out, embed,
                                   adapter_idx)
        if self.dropout_rate:
            out = nn.Dropout(self.dropout_rate, deterministic=not train)(out)
        return out

    def _decode_step(self, q, k, v):
        """Cached attention step.  S > 1 is the PREFILL call — the whole
        prompt runs one ordinary causal attention while its K/V land in
        the cache (one batched MXU-friendly pass, not P single-token
        steps); S == 1 is the incremental decode step attending the
        cached prefix."""
        b, h, s, d = q.shape
        L = self.decode_max_len
        if L <= 0:
            raise ValueError("decode=True needs decode_max_len > 0")
        if self.kv_page_size:
            return self._paged_decode_step(q, k, v)
        cached_k = self.variable(
            "cache", "cached_key",
            lambda: jnp.zeros((b, h, L, d), self.dtype),
        )
        cached_v = self.variable(
            "cache", "cached_value",
            lambda: jnp.zeros((b, h, L, d), self.dtype),
        )
        idx_var = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )
        idx = idx_var.value
        if idx.ndim == 1:
            # Slot-indexed serving mode (serving/engine.py): ``cache_index``
            # is a PER-ROW [B] vector — each batch row (slot) sits at its
            # own sequence position, so rows write K/V at their own index
            # and attend their own valid prefix.  ``s == 1`` is the
            # ordinary decode step; ``s > 1`` is the speculative VERIFY
            # window (speculative.py): a length-``s`` token window lands
            # at each row's own dynamic offset — one dynamic_update_slice
            # per row, shapes static at fixed ``s``, so a fixed draft
            # length K never recompiles — and query position j attends
            # cached positions <= idx + j (the in-window causal rule).
            # Prefill still runs per request at batch 1 with the ordinary
            # scalar index and is inserted into the slot cache afterwards.

            def write_row(cache_row, kv_row, i):
                # [H, L, D] <- [H, s, D] at position i of THIS row only.
                return jax.lax.dynamic_update_slice(
                    cache_row, kv_row, (0, i, 0)
                )

            cached_k.value = jax.vmap(write_row)(
                cached_k.value, k.astype(self.dtype), idx
            )
            cached_v.value = jax.vmap(write_row)(
                cached_v.value, v.astype(self.dtype), idx
            )
            idx_var.value = idx + s
            valid = (
                jnp.arange(L)[None, None, :]
                <= idx[:, None, None] + jnp.arange(s)[None, :, None]
            )[:, None, :, :]
            return attention(
                q, cached_k.value, cached_v.value,
                causal=False, mask=valid, implementation="xla",
            )
        cached_k.value = jax.lax.dynamic_update_slice(
            cached_k.value, k.astype(self.dtype), (0, 0, idx, 0)
        )
        cached_v.value = jax.lax.dynamic_update_slice(
            cached_v.value, v.astype(self.dtype), (0, 0, idx, 0)
        )
        idx_var.value = idx + s
        if s > 1:
            # Prefill: plain causal attention over the prompt itself.  The
            # contract is an EMPTY cache (generate() guarantees it) — a
            # warm-cache multi-token call would silently ignore the cached
            # prefix, so poison the output to NaN instead of being quietly
            # wrong (the index is traced; a static assert cannot see it).
            q = jnp.where(idx == 0, q, jnp.nan)
            return attention(q, k, v, causal=True, implementation="auto")
        # Attend over the valid prefix only: one [1, L] masked row — the
        # decode analog of the causal mask.
        valid = (jnp.arange(L) <= idx)[None, None, None, :]
        return attention(
            q, cached_k.value, cached_v.value,
            causal=False, mask=valid, implementation="xla",
        )

    def _paged_decode_step(self, q, k, v):
        """Paged cached attention (serving/kv_pool.py's memory model).

        K/V live in ONE pool of ``kv_pages`` fixed-size pages
        ``[N, H, page, D]`` shared by every batch row; a per-row
        ``page_table`` ``[B, P]`` (P = decode_max_len / page) maps each
        row's logical position ``i`` to page ``table[row, i // page]``
        at offset ``i % page``.  Writes scatter the length-``s`` window
        at each row's own dynamic offset (the PR2 windowed-append
        discipline: shapes static at fixed ``s``, so ragged join/leave
        traffic and the speculative verify window never recompile);
        reads gather ``pool[table]`` back into logical order
        ``[B, H, P·page, D]`` and attend under the same
        ``arange(L) <= idx + j`` validity mask as the contiguous slot
        path — so a paged row computes bit-for-bit the same attention
        as a contiguous row holding the same K/V.

        Safety invariants (owned by the engine/pool, exploited here):
        page 0 is a TRASH page no live row maps to; inactive rows carry
        an all-zero table, so their writes land in trash instead of
        another row's pages, and positions past a row's allocation also
        resolve to trash.  Positions at or past ``max_len`` (a padded
        continuation window hanging over the end of the sequence) route
        to trash EXPLICITLY — clipping them into the last table slot
        would scatter padding garbage over a full row's real tail K/V.
        """
        b, h, s, d = q.shape
        ps = self.kv_page_size
        L = self.decode_max_len
        if L % ps:
            raise ValueError(
                f"decode_max_len ({L}) must be a multiple of kv_page_size "
                f"({ps}) — the gathered logical length must equal the "
                "contiguous path's for byte-identical attention"
            )
        if self.kv_pages < 2:
            raise ValueError(
                f"kv_pages must be >= 2 (page 0 is the trash page), got "
                f"{self.kv_pages}"
            )
        P = L // ps
        pool_k = self.variable(
            "cache", "cached_key",
            lambda: jnp.zeros((self.kv_pages, h, ps, d), self.dtype),
        )
        pool_v = self.variable(
            "cache", "cached_value",
            lambda: jnp.zeros((self.kv_pages, h, ps, d), self.dtype),
        )
        table_var = self.variable(
            "cache", "page_table", lambda: jnp.zeros((b, P), jnp.int32)
        )
        idx_var = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )
        idx = idx_var.value
        # Init trace reaches here with the scalar init value; broadcast
        # for the (garbage) init compute, keep the stored shape intact.
        idx_vec = idx if idx.ndim == 1 else jnp.full((b,), idx, jnp.int32)
        table = table_var.value

        # -- write: scatter the window at each row's own offset ----------
        positions = idx_vec[:, None] + jnp.arange(s)[None, :]       # [B, s]
        page_slot = jnp.clip(positions // ps, 0, P - 1)
        offs = positions % ps
        page_ids = jnp.where(
            positions < L,
            jnp.take_along_axis(table, page_slot, axis=1),
            0,
        )                                                           # [B, s]

        def scatter(pool, t):  # t: [B, H, s, D] -> rows [B*s, H, D]
            rows = t.astype(pool.dtype).transpose(0, 2, 1, 3)
            rows = rows.reshape(b * s, h, d)
            return pool.at[
                page_ids.reshape(-1), :, offs.reshape(-1), :
            ].set(rows)

        pool_k.value = scatter(pool_k.value, k)
        pool_v.value = scatter(pool_v.value, v)
        idx_var.value = idx + s

        # -- read ---------------------------------------------------------
        if self.paged_kernel and s == 1:
            # Fused path (ops/kernels/paged_attention.py): the kernel
            # pulls pages straight off the table instead of the XLA
            # gather below materializing [B, H, L, D] twice per step.
            # Same mask semantics: lengths = idx + 1 (this step's token
            # included), and the kernel fetches the very pages the
            # gather would — 'auto' resolves to the lax reference
            # (bitwise this gather path) off-TPU.
            from ml_trainer_tpu.ops.kernels.paged_attention import (
                paged_attention,
            )

            out = paged_attention(
                q[:, :, 0, :], pool_k.value, pool_v.value, table,
                idx_vec + 1,
            )
            return out[:, :, None, :]

        # -- read: gather pages back into logical order ------------------
        def gather(pool):  # [B, P, H, page, D] -> [B, H, L, D]
            g = pool[table]
            return g.transpose(0, 2, 1, 3, 4).reshape(b, h, P * ps, d)

        valid = (
            jnp.arange(L)[None, None, :]
            <= idx_vec[:, None, None] + jnp.arange(s)[None, :, None]
        )[:, None, :, :]
        return attention(
            q, gather(pool_k.value), gather(pool_v.value),
            causal=False, mask=valid, implementation="xla",
        )


class MLP(nn.Module):
    """Transformer feed-forward block."""

    hidden_dim: int
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.float32
    activation: Callable = nn.gelu
    # Int8 weight-quantized projections (see MultiHeadAttention).
    quant_int8: bool = False
    # LoRA (see lora_delta / MultiHeadAttention).
    lora_rank: int = 0
    lora_alpha: float = 1.0
    lora_slots: int = 0
    lora_targets: tuple = ()

    @nn.compact
    def __call__(self, x, train: bool = False, adapter_idx=None):
        embed = x.shape[-1]
        if self.quant_int8:
            h = _quant_dense(self, "fc_in", x, self.hidden_dim)
        else:
            h = nn.Dense(self.hidden_dim, dtype=self.dtype, name="fc_in")(x)
        if self.lora_rank and "fc_in" in self.lora_targets:
            h = h + lora_delta(self, "fc_in", x, self.hidden_dim,
                               adapter_idx)
        h = self.activation(h)
        if self.quant_int8:
            out = _quant_dense(self, "fc_out", h, embed)
        else:
            out = nn.Dense(embed, dtype=self.dtype, name="fc_out")(h)
        if self.lora_rank and "fc_out" in self.lora_targets:
            out = out + lora_delta(self, "fc_out", h, embed, adapter_idx)
        if self.dropout_rate:
            out = nn.Dropout(self.dropout_rate, deterministic=not train)(out)
        return out


def remat_policy(name: str):
    """Map a policy name to a jax.checkpoint saveable-filter (shared by
    every transformer family's ``remat_policy`` knob).

    'none': recompute everything in the backward (max memory savings);
    'dots': keep matmul outputs, recompute only the elementwise chain —
    the standard middle ground on TPU, where matmuls are the expensive
    recompute and layernorm/gelu are nearly free."""
    import jax

    if name == "none":
        return None
    if name == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    raise ValueError(
        f"Unknown remat_policy {name!r}; expected 'none' or 'dots'"
    )


def remat_block(remat: bool, policy_name: str = "none"):
    """The TransformerBlock constructor, wrapped in jax.checkpoint when
    ``remat`` — one definition of the (static_argnums, policy) plumbing
    for the gpt2/vit/bert families."""
    if not remat:
        return TransformerBlock
    return nn.remat(
        TransformerBlock, static_argnums=(3,),
        policy=remat_policy(policy_name),
    )


class TransformerBlock(nn.Module):
    """Pre-LN transformer block (the GPT-2/ViT arrangement; BERT uses
    post-LN via the ``post_norm`` flag)."""

    num_heads: int
    mlp_dim: int
    causal: bool = False
    dropout_rate: float = 0.0
    post_norm: bool = False
    dtype: jnp.dtype = jnp.float32
    attention_impl: str = "auto"
    mesh: Optional[object] = None
    moe_experts: int = 0  # >0: MoE feed-forward (expert parallelism)
    moe_top_k: int = 1    # experts per token (1 = Switch, 2 = GShard)
    decode: bool = False  # KV-cached single-token mode (see MultiHeadAttention)
    decode_max_len: int = 0
    kv_page_size: int = 0  # >0: paged KV pool (see MultiHeadAttention)
    kv_pages: int = 0
    paged_kernel: bool = False  # fused paged-attention decode kernel
    quant_int8: bool = False    # int8 weight-quantized projections
    # LoRA (see lora_delta): threaded to the attention/MLP projections.
    lora_rank: int = 0
    lora_alpha: float = 1.0
    lora_slots: int = 0
    lora_targets: tuple = ()

    @nn.compact
    def __call__(self, x, mask=None, train: bool = False, kv_lens=None,
                 adapter_idx=None):
        lora_kw = dict(
            lora_rank=self.lora_rank, lora_alpha=self.lora_alpha,
            lora_slots=self.lora_slots, lora_targets=self.lora_targets,
        ) if self.lora_rank else {}
        attn = lambda y: MultiHeadAttention(
            self.num_heads, causal=self.causal, dropout_rate=self.dropout_rate,
            dtype=self.dtype, attention_impl=self.attention_impl,
            mesh=self.mesh, decode=self.decode,
            decode_max_len=self.decode_max_len,
            kv_page_size=self.kv_page_size, kv_pages=self.kv_pages,
            paged_kernel=self.paged_kernel, quant_int8=self.quant_int8,
            name="attn", **lora_kw,
        )(y, mask=mask, train=train, kv_lens=kv_lens,
          **({"adapter_idx": adapter_idx} if self.lora_rank else {}))
        if self.moe_experts:
            from ml_trainer_tpu.models.moe import MoEMLP

            mlp = lambda y: MoEMLP(
                self.moe_experts, self.mlp_dim,
                num_selected=self.moe_top_k, dtype=self.dtype, name="mlp",
            )(y, train=train)
        else:
            mlp = lambda y: MLP(
                self.mlp_dim, dropout_rate=self.dropout_rate, dtype=self.dtype,
                quant_int8=self.quant_int8, name="mlp", **lora_kw,
            )(y, train=train,
              **({"adapter_idx": adapter_idx} if self.lora_rank else {}))
        ln1 = nn.LayerNorm(dtype=self.dtype, name="ln1")
        ln2 = nn.LayerNorm(dtype=self.dtype, name="ln2")
        if self.post_norm:  # BERT-style
            x = ln1(x + attn(x))
            x = ln2(x + mlp(x))
        else:  # GPT-2/ViT-style
            x = x + attn(ln1(x))
            x = x + mlp(ln2(x))
        return x
