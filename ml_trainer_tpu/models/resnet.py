"""ResNet-18/50, NHWC flax — the BASELINE.json configs[0..1] models.

TPU-first choices: NHWC layout throughout, 3×3/1×1 convs sized for MXU
tiling.  BatchNorm statistics under data parallelism are GLOBAL-batch:
inside ``jit`` the batch mean/var are computed over the whole sharded
batch (XLA inserts the cross-device reduction the sharding implies) —
i.e. the SyncBN arrangement, not torch DDP's local per-replica stats
(ref: src/trainer.py:98).  That is exactly why the DP-equals-single-device
trajectory test holds bit-for-bit.  A ``cifar_stem`` variant
replaces the 7×7/stride-2 + maxpool stem with a 3×3/stride-1 conv so
ResNet-18 trains sensibly on 32×32 inputs (the local-path config).
"""

from __future__ import annotations

from typing import Sequence, Type

import flax.linen as nn
import jax.numpy as jnp

from ml_trainer_tpu.models.registry import register_model


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = lambda name: nn.BatchNorm(
            use_running_average=not train, momentum=0.9, name=name,
            dtype=self.dtype,
        )
        residual = x
        y = nn.Conv(self.filters, (3, 3), (self.strides, self.strides),
                    padding="SAME", use_bias=False, dtype=self.dtype,
                    name="conv1")(x)
        y = nn.relu(norm("bn1")(y))
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype, name="conv2")(y)
        y = norm("bn2")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1),
                               (self.strides, self.strides), use_bias=False,
                               dtype=self.dtype, name="downsample")(x)
            residual = norm("bn_down")(residual)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: jnp.dtype = jnp.float32
    expansion: int = 4

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = lambda name: nn.BatchNorm(
            use_running_average=not train, momentum=0.9, name=name,
            dtype=self.dtype,
        )
        out_filters = self.filters * self.expansion
        residual = x
        y = nn.Conv(self.filters, (1, 1), use_bias=False, dtype=self.dtype,
                    name="conv1")(x)
        y = nn.relu(norm("bn1")(y))
        y = nn.Conv(self.filters, (3, 3), (self.strides, self.strides),
                    padding="SAME", use_bias=False, dtype=self.dtype,
                    name="conv2")(y)
        y = nn.relu(norm("bn2")(y))
        y = nn.Conv(out_filters, (1, 1), use_bias=False, dtype=self.dtype,
                    name="conv3")(y)
        y = norm("bn3")(y)
        if residual.shape != y.shape:
            residual = nn.Conv(out_filters, (1, 1),
                               (self.strides, self.strides), use_bias=False,
                               dtype=self.dtype, name="downsample")(x)
            residual = norm("bn_down")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block: Type[nn.Module]
    num_classes: int = 1000
    cifar_stem: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.cifar_stem:
            x = nn.Conv(64, (3, 3), padding="SAME", use_bias=False,
                        dtype=self.dtype, name="stem_conv")(x)
        else:
            x = nn.Conv(64, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                        use_bias=False, dtype=self.dtype, name="stem_conv")(x)
        x = nn.relu(
            nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=self.dtype, name="stem_bn")(x)
        )
        if not self.cifar_stem:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for stage, num_blocks in enumerate(self.stage_sizes):
            for b in range(num_blocks):
                strides = 2 if (stage > 0 and b == 0) else 1
                x = self.block(
                    filters=64 * 2 ** stage, strides=strides,
                    dtype=self.dtype, name=f"stage{stage + 1}_block{b + 1}",
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


@register_model("resnet18")
def resnet18(num_classes: int = 10, cifar_stem: bool = True,
             dtype=jnp.float32) -> ResNet:
    """ResNet-18 (CIFAR-10 local-path config, BASELINE.json configs[0])."""
    return ResNet([2, 2, 2, 2], BasicBlock, num_classes=num_classes,
                  cifar_stem=cifar_stem, dtype=dtype)


@register_model("resnet50")
def resnet50(num_classes: int = 1000, cifar_stem: bool = False,
             dtype=jnp.float32) -> ResNet:
    """ResNet-50 (ImageNet DP north-star config, BASELINE.json configs[1])."""
    return ResNet([3, 4, 6, 3], BottleneckBlock, num_classes=num_classes,
                  cifar_stem=cifar_stem, dtype=dtype)


@register_model("resnet101")
def resnet101(num_classes: int = 1000, cifar_stem: bool = False,
              dtype=jnp.float32) -> ResNet:
    """ResNet-101: the [3, 4, 23, 3] bottleneck stack."""
    return ResNet([3, 4, 23, 3], BottleneckBlock, num_classes=num_classes,
                  cifar_stem=cifar_stem, dtype=dtype)


@register_model("resnet152")
def resnet152(num_classes: int = 1000, cifar_stem: bool = False,
              dtype=jnp.float32) -> ResNet:
    """ResNet-152: the [3, 8, 36, 3] bottleneck stack."""
    return ResNet([3, 8, 36, 3], BottleneckBlock, num_classes=num_classes,
                  cifar_stem=cifar_stem, dtype=dtype)
