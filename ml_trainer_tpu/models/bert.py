"""BERT-base encoder + sequence-classification head — the SST-2 fine-tune
north-star config (BASELINE.json configs[2], the tokenized-dataset path).

Post-LN encoder blocks (the original BERT arrangement) over token +
position + segment embeddings; classification from the [CLS] position
through a tanh pooler.  Padding is handled with an attention mask built
from ``attention_mask`` input (1 = keep), threaded to ops.attention.

``right_padded=True`` (opt-in) declares every attention mask a contiguous
prefix (standard right-padded tokenizer output, like this framework's
``TokenizedDataset``): the mask is then ALSO summarized into per-sequence
valid-key counts (``kv_lens``) so padded batches run the fused Pallas
flash kernel instead of the XLA mask fallback.  The default is False —
exact for ARBITRARY masks via the XLA path — because a non-prefix mask
under ``right_padded=True`` would be silently mis-masked on the flash
path (lengths cannot represent holes); opt in only where right padding
holds by construction.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from ml_trainer_tpu.models.layers import remat_block
from ml_trainer_tpu.models.registry import register_model


class BertEncoder(nn.Module):
    vocab_size: int = 30522
    max_len: int = 512
    type_vocab_size: int = 2
    embed_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    dropout_rate: float = 0.0
    num_classes: Optional[int] = 2
    pad_token_id: int = 0
    dtype: jnp.dtype = jnp.float32
    attention_impl: str = "auto"
    remat: bool = False  # jax.checkpoint each block (backward recompute)
    remat_policy: str = "none"  # 'dots' keeps matmul outputs (see layers.remat_policy)
    right_padded: bool = False  # opt-in: masks are contiguous prefixes

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None,
                 train: bool = False):
        b, s = input_ids.shape
        if attention_mask is None:
            # Derive padding mask from the pad token so the plain (ids,
            # labels) Loader path masks correctly without a side channel.
            attention_mask = (input_ids != self.pad_token_id).astype(jnp.int32)
        tok = nn.Embed(self.vocab_size, self.embed_dim, name="tok_embed")(
            input_ids
        )
        pos_ids = jnp.arange(s)[None, :]
        pos = nn.Embed(self.max_len, self.embed_dim, name="pos_embed")(pos_ids)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        seg = nn.Embed(self.type_vocab_size, self.embed_dim, name="seg_embed")(
            token_type_ids
        )
        x = (tok + pos + seg).astype(self.dtype)
        x = nn.LayerNorm(dtype=self.dtype, name="embed_ln")(x)
        if self.dropout_rate:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        mask = None
        kv_lens = None
        if attention_mask is not None:
            # [B, S] (1 = real token) -> [B, 1, 1, S] broadcastable boolean.
            mask = attention_mask[:, None, None, :].astype(bool)
            if self.right_padded:
                # Right-padded masks compress to valid-key counts, which the
                # flash kernel fuses (ops.attention kv_lens); clamp to >= 1
                # so an all-pad row still has a defined softmax.
                kv_lens = jnp.maximum(
                    attention_mask.astype(jnp.int32).sum(axis=-1), 1
                )
        Block = remat_block(self.remat, self.remat_policy)
        for i in range(self.depth):
            x = Block(
                num_heads=self.num_heads, mlp_dim=self.mlp_dim,
                dropout_rate=self.dropout_rate, post_norm=True,
                dtype=self.dtype, attention_impl=self.attention_impl,
                name=f"layer{i}",
            )(x, mask, train, kv_lens)
        if self.num_classes is None:
            return x  # sequence output (feature-extractor mode)
        pooled = jnp.tanh(
            nn.Dense(self.embed_dim, dtype=jnp.float32, name="pooler")(x[:, 0])
        )
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="classifier")(
            pooled
        )


@register_model("bert_base")
def bert_base(num_classes: int = 2, **kw) -> BertEncoder:
    """BERT-base: 12 layers, 768 wide, 12 heads (SST-2 head by default)."""
    return BertEncoder(num_classes=num_classes, **kw)


@register_model("bert_large")
def bert_large(num_classes: int = 2, **kw) -> BertEncoder:
    """BERT-large: 24 layers, 1024 wide, 16 heads."""
    kw.setdefault("embed_dim", 1024)
    kw.setdefault("depth", 24)
    kw.setdefault("num_heads", 16)
    kw.setdefault("mlp_dim", 4096)
    return BertEncoder(num_classes=num_classes, **kw)


@register_model("bert_tiny")
def bert_tiny(num_classes: int = 2, **kw) -> BertEncoder:
    """Small BERT for tests: 2 layers, 128 wide."""
    kw.setdefault("vocab_size", 1024)
    kw.setdefault("embed_dim", 128)
    kw.setdefault("depth", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("mlp_dim", 256)
    kw.setdefault("max_len", 128)
    return BertEncoder(num_classes=num_classes, **kw)
