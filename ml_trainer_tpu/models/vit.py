"""ViT-B/16 — the bf16 mixed-precision north-star config
(BASELINE.json configs[3]).

Patchify is a single strided conv (one big MXU matmul per image), encoder is
the shared pre-LN TransformerBlock stack, classification via the prepended
CLS token.  ``dtype=bfloat16`` runs every activation matmul in bf16 on the
MXU while params and the final head stay f32.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from ml_trainer_tpu.models.layers import remat_block
from ml_trainer_tpu.models.registry import register_model


class VisionTransformer(nn.Module):
    num_classes: int = 1000
    patch_size: int = 16
    embed_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.float32
    attention_impl: str = "auto"
    remat: bool = False  # jax.checkpoint each block (backward recompute)
    remat_policy: str = "none"  # 'dots' keeps matmul outputs (see layers.remat_policy)

    @nn.compact
    def __call__(self, x, train: bool = False):
        b = x.shape[0]
        p = self.patch_size
        x = nn.Conv(self.embed_dim, (p, p), strides=(p, p), padding="VALID",
                    dtype=self.dtype, name="patch_embed")(x)
        x = x.reshape(b, -1, self.embed_dim)  # [B, num_patches, E]
        cls = self.param("cls_token", nn.initializers.zeros,
                         (1, 1, self.embed_dim))
        x = jnp.concatenate([jnp.broadcast_to(cls, (b, 1, self.embed_dim)).astype(x.dtype), x], axis=1)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, x.shape[1], self.embed_dim))
        x = x + pos.astype(x.dtype)
        if self.dropout_rate:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        Block = remat_block(self.remat, self.remat_policy)
        for i in range(self.depth):
            x = Block(
                num_heads=self.num_heads, mlp_dim=self.mlp_dim,
                dropout_rate=self.dropout_rate, dtype=self.dtype,
                attention_impl=self.attention_impl, name=f"block{i}",
            )(x, None, train)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_final")(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x[:, 0])


@register_model("vit_b16")
def vit_b16(num_classes: int = 1000, dtype=jnp.bfloat16, **kw) -> VisionTransformer:
    """ViT-B/16: 12 layers, 768 wide, 12 heads — bf16 by default."""
    return VisionTransformer(num_classes=num_classes, dtype=dtype, **kw)


@register_model("vit_tiny")
def vit_tiny(num_classes: int = 10, **kw) -> VisionTransformer:
    """Small ViT for tests: 2 layers, 128 wide, patch 8."""
    kw.setdefault("patch_size", 8)
    kw.setdefault("embed_dim", 128)
    kw.setdefault("depth", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("mlp_dim", 256)
    return VisionTransformer(num_classes=num_classes, **kw)
