"""Llama-family decoder: RMSNorm + RoPE + grouped-query attention + SwiGLU.

No analog in the reference (its only model is a 62K-param CNN,
ref: src/model.py) and beyond the north-star zoo — this is the modern
LM architecture the framework must also serve to be a complete
training stack.  Everything rides the existing TPU-first machinery:
attention flows through ``ops.attention`` (flash kernel on causal
tile-aligned shapes), the chunked LM loss keeps the [B, S, V] logits
unmaterialized, per-block remat reuses the shared policies, and
KV-cached generation works through ``generate()`` unchanged — with the
GQA twist that the cache stores the UN-repeated ``num_kv_heads`` K/V
(the whole point of GQA: an H/Hkv-times smaller inference cache).

Architectural choices match the published Llama arrangement: pre-RMSNorm
blocks, rotary embeddings applied to q/k per head (rotate-half
convention), no biases anywhere, SwiGLU feed-forward, untied LM head.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ml_trainer_tpu.models.registry import register_model
from ml_trainer_tpu.ops.attention import attention


def apply_rope(x: jax.Array, positions: jax.Array,
               theta: float = 10000.0) -> jax.Array:
    """Rotary position embedding, rotate-half convention.

    x: [B, H, S, D] (D even); positions: [S] absolute token positions.
    Angles are computed in f32 regardless of activation dtype (bf16
    angles at position ~1000 lose the low bits that distinguish
    neighboring positions), result cast back."""
    d = x.shape[-1]
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]  # [S, half]
    cos = jnp.cos(angles)[None, None]
    sin = jnp.sin(angles)[None, None]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )
    return out.astype(x.dtype)


class GQAttention(nn.Module):
    """Grouped-query attention: ``num_heads`` query heads share
    ``num_kv_heads`` key/value heads (H % Hkv == 0).  K/V are repeated
    up to H only at the attention compute; projections, the decode
    cache, and (in decode) HBM traffic all stay at the Hkv width."""

    num_heads: int
    num_kv_heads: int
    head_dim: int
    rope_theta: float = 10000.0
    dtype: jnp.dtype = jnp.float32
    attention_impl: str = "auto"
    mesh: Optional[object] = None
    decode: bool = False
    decode_max_len: int = 0

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.num_heads % self.num_kv_heads:
            raise ValueError(
                f"num_heads {self.num_heads} not divisible by "
                f"num_kv_heads {self.num_kv_heads}"
            )
        b, s, _ = x.shape
        h, hk, d = self.num_heads, self.num_kv_heads, self.head_dim
        dense = lambda n, name: nn.Dense(  # noqa: E731
            n, use_bias=False, dtype=self.dtype, name=name
        )
        q = dense(h * d, "q")(x).reshape(b, s, h, d).transpose(0, 2, 1, 3)
        k = dense(hk * d, "k")(x).reshape(b, s, hk, d).transpose(0, 2, 1, 3)
        v = dense(hk * d, "v")(x).reshape(b, s, hk, d).transpose(0, 2, 1, 3)

        if self.decode:
            out = self._decode_step(q, k, v)
        else:
            positions = jnp.arange(s)
            q = apply_rope(q, positions, self.rope_theta)
            k = apply_rope(k, positions, self.rope_theta)
            out = attention(
                q, jnp.repeat(k, h // hk, axis=1),
                jnp.repeat(v, h // hk, axis=1),
                causal=True, implementation=self.attention_impl,
                mesh=self.mesh,
            )
        out = out.transpose(0, 2, 1, 3).reshape(b, s, h * d)
        return dense(x.shape[-1], "proj")(out)

    def _decode_step(self, q, k, v):
        """KV-cached decode (see layers.MultiHeadAttention._decode_step —
        same contract: S>1 is the empty-cache prefill, S==1 incremental).
        RoPE is applied BEFORE caching K, so cached keys already carry
        their absolute positions; the cache holds Hkv heads."""
        b, h, s, d = q.shape
        hk = self.num_kv_heads
        L = self.decode_max_len
        if L <= 0:
            raise ValueError("decode=True needs decode_max_len > 0")
        cached_k = self.variable(
            "cache", "cached_key", lambda: jnp.zeros((b, hk, L, d), self.dtype)
        )
        cached_v = self.variable(
            "cache", "cached_value",
            lambda: jnp.zeros((b, hk, L, d), self.dtype),
        )
        idx_var = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )
        idx = idx_var.value
        positions = idx + jnp.arange(s)
        q = apply_rope(q, positions, self.rope_theta)
        k = apply_rope(k, positions, self.rope_theta)
        cached_k.value = jax.lax.dynamic_update_slice(
            cached_k.value, k.astype(self.dtype), (0, 0, idx, 0)
        )
        cached_v.value = jax.lax.dynamic_update_slice(
            cached_v.value, v.astype(self.dtype), (0, 0, idx, 0)
        )
        idx_var.value = idx + s
        rep = h // hk
        if s > 1:
            # Prefill over the prompt itself (empty-cache contract; see
            # layers.py for the NaN poisoning rationale).
            q = jnp.where(idx == 0, q, jnp.nan)
            return attention(
                q, jnp.repeat(k, rep, axis=1), jnp.repeat(v, rep, axis=1),
                causal=True, implementation="auto",
            )
        valid = (jnp.arange(L) <= idx)[None, None, None, :]
        return attention(
            q,
            jnp.repeat(cached_k.value, rep, axis=1),
            jnp.repeat(cached_v.value, rep, axis=1),
            causal=False, mask=valid, implementation="xla",
        )


class LlamaBlock(nn.Module):
    num_heads: int
    num_kv_heads: int
    head_dim: int
    hidden_dim: int
    rope_theta: float = 10000.0
    dtype: jnp.dtype = jnp.float32
    attention_impl: str = "auto"
    mesh: Optional[object] = None
    decode: bool = False
    decode_max_len: int = 0

    @nn.compact
    def __call__(self, x, train: bool = False):
        attn = GQAttention(
            self.num_heads, self.num_kv_heads, self.head_dim,
            rope_theta=self.rope_theta, dtype=self.dtype,
            attention_impl=self.attention_impl, mesh=self.mesh,
            decode=self.decode, decode_max_len=self.decode_max_len,
            name="attn",
        )
        x = x + attn(nn.RMSNorm(dtype=self.dtype, name="ln1")(x), train=train)
        y = nn.RMSNorm(dtype=self.dtype, name="ln2")(x)
        # SwiGLU: down(silu(gate(y)) * up(y)) — the Llama feed-forward.
        dense = lambda n, name: nn.Dense(  # noqa: E731
            n, use_bias=False, dtype=self.dtype, name=name
        )
        y = dense(x.shape[-1], "down")(
            nn.silu(dense(self.hidden_dim, "gate")(y))
            * dense(self.hidden_dim, "up")(y)
        )
        return x + y


class LlamaLM(nn.Module):
    """Causal Llama-style LM.  ``targets`` (with ``loss_chunk`` > 0)
    switches to the model-computed chunked loss — the untied lm_head
    kernel plays the embedding-matrix role, so the [B, S, V] logits are
    never materialized (ops/losses.chunked_lm_cross_entropy)."""

    vocab_size: int = 32000
    max_len: int = 2048
    embed_dim: int = 768
    depth: int = 12
    num_heads: int = 12
    num_kv_heads: int = 4
    hidden_dim: int = 0  # 0 -> the Llama default ~8/3 * embed, rounded
    rope_theta: float = 10000.0
    dtype: jnp.dtype = jnp.float32
    attention_impl: str = "auto"
    mesh: Optional[object] = None
    remat: bool = False
    remat_policy: str = "none"
    loss_chunk: int = 0
    decode: bool = False

    @nn.compact
    def __call__(self, input_ids, train: bool = False, targets=None):
        from ml_trainer_tpu.models.layers import remat_policy

        hidden = self.hidden_dim or int(
            ((8 * self.embed_dim // 3) + 127) // 128 * 128
        )
        head_dim = self.embed_dim // self.num_heads
        x = nn.Embed(
            self.vocab_size, self.embed_dim, dtype=self.dtype,
            name="tok_embed",
        )(input_ids)
        Block = LlamaBlock
        if self.remat:
            Block = nn.remat(
                LlamaBlock, static_argnums=(2,),
                policy=remat_policy(self.remat_policy),
            )
        for i in range(self.depth):
            x = Block(
                self.num_heads, self.num_kv_heads, head_dim, hidden,
                rope_theta=self.rope_theta, dtype=self.dtype,
                attention_impl=self.attention_impl, mesh=self.mesh,
                decode=self.decode,
                decode_max_len=self.max_len if self.decode else 0,
                name=f"block{i}",
            )(x, train)
        x = nn.RMSNorm(dtype=self.dtype, name="ln_final")(x)
        lm_head = self.param(
            "lm_head",
            nn.initializers.normal(0.02),
            (self.embed_dim, self.vocab_size),
            jnp.float32,
        )
        if targets is not None:
            if not self.loss_chunk:
                raise ValueError(
                    "targets requires loss_chunk > 0 (a divisor of the "
                    "sequence length)"
                )
            from ml_trainer_tpu.ops.losses import chunked_lm_cross_entropy

            return chunked_lm_cross_entropy(
                x, lm_head.T, targets, self.loss_chunk
            )
        return x.astype(jnp.float32) @ lm_head.astype(jnp.float32)


@register_model("llama")
def llama(**kw) -> LlamaLM:
    """~160M Llama-style config (GQA 12q/4kv, SwiGLU, RoPE)."""
    return LlamaLM(**kw)


@register_model("llama_tiny")
def llama_tiny(**kw) -> LlamaLM:
    kw.setdefault("vocab_size", 1024)
    kw.setdefault("max_len", 128)
    kw.setdefault("embed_dim", 64)
    kw.setdefault("depth", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("num_kv_heads", 2)
    return LlamaLM(**kw)
