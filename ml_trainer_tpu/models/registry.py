"""Model registry: string name -> flax module factory.

The reference has exactly one hardcoded model (ref: main.py:30); the
registry generalizes that to the north-star zoo while keeping
``Trainer(model=...)`` able to accept either a module instance or a name.
"""

from __future__ import annotations

import importlib
from typing import Callable, Dict

import flax.linen as nn

MODELS: Dict[str, Callable[..., nn.Module]] = {}

# Target-model name -> suggested draft-model name for speculative
# decoding (ml_trainer_tpu/speculative.py).  A valid pair shares one
# vocabulary — acceptance compares token ids across the two models — so
# the pairing is registered next to the models instead of guessed at
# call sites.
DRAFT_PAIRS: Dict[str, str] = {
    "gpt2_mini": "gpt2_nano",
    # The 50257-vocab family has no small partner in the zoo yet
    # (gpt2_tiny's synthetic 1024 vocab is NOT compatible); the n-gram
    # drafter covers those targets model-free.
}

_FAMILY_MODULES = ("mlmodel", "resnet", "vit", "bert", "gpt2", "llama")


def register_model(name: str):
    def deco(factory):
        MODELS[name] = factory
        return factory

    return deco


def _load_families() -> None:
    for mod in _FAMILY_MODULES:
        try:
            importlib.import_module(f"ml_trainer_tpu.models.{mod}")
        except ImportError:
            pass


def get_model(name: str, **kwargs) -> nn.Module:
    _load_families()
    try:
        return MODELS[name](**kwargs)
    except KeyError:
        raise ValueError(
            f"Unknown model {name!r}; expected one of {sorted(MODELS)}"
        ) from None


def available_models():
    _load_families()
    return sorted(MODELS)


def suggested_draft(name: str, **kwargs) -> nn.Module:
    """Build the registered draft-model partner of target ``name`` (for
    speculative decoding).  Raises ``ValueError`` when no pairing is
    registered — callers should then fall back to the model-free n-gram
    drafter rather than guess a vocabulary-incompatible model."""
    if name not in DRAFT_PAIRS:
        raise ValueError(
            f"no draft model registered for {name!r} "
            f"(known pairs: {sorted(DRAFT_PAIRS)}); use the n-gram "
            "lookup drafter instead"
        )
    return get_model(DRAFT_PAIRS[name], **kwargs)
