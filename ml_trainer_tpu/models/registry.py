"""Model registry: string name -> flax module factory.

The reference has exactly one hardcoded model (ref: main.py:30); the
registry generalizes that to the north-star zoo while keeping
``Trainer(model=...)`` able to accept either a module instance or a name.
"""

from __future__ import annotations

import importlib
from typing import Callable, Dict

import flax.linen as nn

MODELS: Dict[str, Callable[..., nn.Module]] = {}

_FAMILY_MODULES = ("mlmodel", "resnet", "vit", "bert", "gpt2", "llama")


def register_model(name: str):
    def deco(factory):
        MODELS[name] = factory
        return factory

    return deco


def _load_families() -> None:
    for mod in _FAMILY_MODULES:
        try:
            importlib.import_module(f"ml_trainer_tpu.models.{mod}")
        except ImportError:
            pass


def get_model(name: str, **kwargs) -> nn.Module:
    _load_families()
    try:
        return MODELS[name](**kwargs)
    except KeyError:
        raise ValueError(
            f"Unknown model {name!r}; expected one of {sorted(MODELS)}"
        ) from None


def available_models():
    _load_families()
    return sorted(MODELS)
