"""Model registry: string name -> flax module factory.

The reference has exactly one hardcoded model (ref: main.py:30); the
registry generalizes that to the north-star zoo while keeping
``Trainer(model=...)`` able to accept either a module instance or a name.
"""

from __future__ import annotations

import importlib
from typing import Callable, Dict

import flax.linen as nn

MODELS: Dict[str, Callable[..., nn.Module]] = {}

# Target-model name -> suggested draft-model name for speculative
# decoding (ml_trainer_tpu/speculative.py).  A valid pair shares one
# vocabulary — acceptance compares token ids across the two models — so
# the pairing is registered next to the models instead of guessed at
# call sites.
DRAFT_PAIRS: Dict[str, str] = {
    "gpt2_mini": "gpt2_nano",
    # The 50257-vocab family has no small partner in the zoo yet
    # (gpt2_tiny's synthetic 1024 vocab is NOT compatible); the n-gram
    # drafter covers those targets model-free.
}

_FAMILY_MODULES = ("mlmodel", "resnet", "vit", "bert", "gpt2", "llama")


def register_model(name: str):
    def deco(factory):
        MODELS[name] = factory
        return factory

    return deco


def _load_families() -> None:
    for mod in _FAMILY_MODULES:
        try:
            importlib.import_module(f"ml_trainer_tpu.models.{mod}")
        except ImportError:
            pass


def get_model(name: str, **kwargs) -> nn.Module:
    """Build a registered model.  ``precision=`` (a policy name like
    ``'bf16'`` or a ``precision.Precision``) threads the policy's compute
    dtype onto the module's ``dtype`` knob for the families that carry
    one (the transformer zoo computes activations in ``dtype`` while
    params stay fp32 — exactly the mixed-precision split); families
    without a ``dtype`` field (mlmodel/resnet) ignore it here and rely
    on the Trainer's generic cast-at-apply instead."""
    _load_families()
    precision = kwargs.pop("precision", None)
    if precision is not None and "dtype" not in kwargs:
        from ml_trainer_tpu.precision import resolve_precision

        policy = resolve_precision(precision)
        if policy.active:
            kwargs["dtype"] = policy.compute
    try:
        factory = MODELS[name]
    except KeyError:
        raise ValueError(
            f"Unknown model {name!r}; expected one of {sorted(MODELS)}"
        ) from None
    try:
        return factory(**kwargs)
    except TypeError:
        if "dtype" in kwargs and precision is not None:
            # Family without a dtype knob: drop the threaded compute dtype
            # (the Trainer-level cast covers these models).
            kwargs.pop("dtype")
            return factory(**kwargs)
        raise


def available_models():
    _load_families()
    return sorted(MODELS)


def suggested_draft(name: str, **kwargs) -> nn.Module:
    """Build the registered draft-model partner of target ``name`` (for
    speculative decoding).  Raises ``ValueError`` when no pairing is
    registered — callers should then fall back to the model-free n-gram
    drafter rather than guess a vocabulary-incompatible model."""
    if name not in DRAFT_PAIRS:
        raise ValueError(
            f"no draft model registered for {name!r} "
            f"(known pairs: {sorted(DRAFT_PAIRS)}); use the n-gram "
            "lookup drafter instead"
        )
    return get_model(DRAFT_PAIRS[name], **kwargs)
