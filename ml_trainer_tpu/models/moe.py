"""Mixture-of-Experts feed-forward — expert parallelism over the ``expert``
mesh axis.

The reference has no MoE (SURVEY.md §2C: expert parallel "not required");
this fills the reserved ``expert`` axis with the TPU-idiomatic GShard/
Mesh-TensorFlow formulation: experts live as ONE stacked parameter
[E, ...] sharded ``P('expert', ...)``, and routing is dense einsum algebra
over a capacity-bounded one-hot dispatch tensor — no gather/scatter, no
data-dependent shapes, so XLA lowers the whole layer onto the MXU and turns
the expert-axis shardings into the dispatch all-to-alls.

Top-1 routing (Switch-Transformer style) with capacity factor + auxiliary
load-balance loss (reported via ``self.sow`` so trainers can add it).
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoEMLP(nn.Module):
    """Drop-in replacement for the dense transformer MLP block.

    x: [B, S, M] -> [B, S, M]; E experts each an (M -> hidden -> M) MLP.
    Tokens route to their top-1 expert, bounded by
    ``capacity = ceil(capacity_factor * tokens / E)`` per expert; overflow
    tokens fall through the residual (output 0 for the MLP branch).
    """

    num_experts: int
    hidden_dim: int
    capacity_factor: float = 1.25
    dtype: jnp.dtype = jnp.float32
    activation: Callable = nn.gelu

    @nn.compact
    def __call__(self, x, train: bool = False):
        b, s, m = x.shape
        e = self.num_experts
        tokens = b * s
        capacity = max(int(self.capacity_factor * tokens / e), 1)
        xt = x.reshape(tokens, m)

        # Router (always f32 — small matmul, numerics matter).
        router = nn.Dense(e, dtype=jnp.float32, name="router")
        probs = jax.nn.softmax(router(xt.astype(jnp.float32)), axis=-1)

        expert_idx = jnp.argmax(probs, axis=-1)                # [T]
        expert_mask = jax.nn.one_hot(expert_idx, e)            # [T, E]
        gate = jnp.sum(probs * expert_mask, axis=-1)           # [T]

        # Switch-Transformer load-balance loss: E * sum(fraction * prob).
        fraction = jnp.mean(expert_mask, axis=0)
        prob_mean = jnp.mean(probs, axis=0)
        self.sow(
            "losses", "moe_aux_loss",
            e * jnp.sum(fraction * prob_mean),
        )

        # Position of each token within its expert's capacity buffer;
        # tokens past capacity are dropped (residual passes them through).
        position = jnp.cumsum(expert_mask, axis=0) * expert_mask - 1.0
        keep = (position < capacity) & (expert_mask > 0)        # [T, E]
        onehot_pos = jax.nn.one_hot(
            jnp.clip(position, 0, capacity - 1).astype(jnp.int32), capacity
        )                                                       # [T, E, C]
        dispatch = onehot_pos * keep[..., None]                 # [T, E, C]
        combine = dispatch * gate[:, None, None]                # [T, E, C]

        # Stacked expert weights, sharded over the expert mesh axis by the
        # EP_RULES PartitionSpecs (parallel/tp_rules.py).
        wi = self.param(
            "wi", nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, m, self.hidden_dim),
        )
        wo = self.param(
            "wo", nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, self.hidden_dim, m),
        )

        xin = jnp.einsum(
            "tec,tm->ecm", dispatch.astype(self.dtype), xt.astype(self.dtype)
        )                                                       # [E, C, M]
        h = self.activation(
            jnp.einsum("ecm,emh->ech", xin, wi.astype(self.dtype))
        )
        xout = jnp.einsum("ech,ehm->ecm", h, wo.astype(self.dtype))
        out = jnp.einsum(
            "tec,ecm->tm", combine.astype(self.dtype), xout
        )
        return out.reshape(b, s, m).astype(x.dtype)
