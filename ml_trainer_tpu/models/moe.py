"""Mixture-of-Experts feed-forward — expert parallelism over the ``expert``
mesh axis.

The reference has no MoE (SURVEY.md §2C: expert parallel "not required");
this fills the reserved ``expert`` axis with the TPU-idiomatic GShard/
Mesh-TensorFlow formulation: experts live as ONE stacked parameter
[E, ...] sharded ``P('expert', ...)``, and routing is dense einsum algebra
over a capacity-bounded one-hot dispatch tensor — no gather/scatter, no
data-dependent shapes, so XLA lowers the whole layer onto the MXU and turns
the expert-axis shardings into the dispatch all-to-alls.

Top-1 routing (Switch-Transformer style) by default; ``num_selected=2``
gives GShard-style top-2 with renormalized gates and priority dispatch
(all first choices claim capacity before any second choice).  Capacity
factor + auxiliary load-balance loss (reported via ``self.sow`` so
trainers can add it) apply to both.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoEMLP(nn.Module):
    """Drop-in replacement for the dense transformer MLP block.

    x: [B, S, M] -> [B, S, M]; E experts each an (M -> hidden -> M) MLP.
    Tokens route to their top-``num_selected`` experts, bounded by
    ``capacity = floor(capacity_factor * tokens * num_selected / E)``
    (min 1) per expert; overflow tokens fall through the residual
    (output 0 for the MLP branch).  With ``num_selected > 1`` gates renormalize over the
    selected experts (GShard) — at 1 the raw router probability is the
    gate (Switch), so the default reproduces the original behavior
    exactly.
    """

    num_experts: int
    hidden_dim: int
    capacity_factor: float = 1.25
    num_selected: int = 1
    dtype: jnp.dtype = jnp.float32
    activation: Callable = nn.gelu

    @nn.compact
    def __call__(self, x, train: bool = False):
        b, s, m = x.shape
        e = self.num_experts
        kk = self.num_selected
        if not 1 <= kk <= e:
            raise ValueError(
                f"num_selected must be in [1, num_experts={e}], got {kk}"
            )
        tokens = b * s
        capacity = max(int(self.capacity_factor * tokens * kk / e), 1)
        xt = x.reshape(tokens, m)

        # Router (always f32 — small matmul, numerics matter).
        router = nn.Dense(e, dtype=jnp.float32, name="router")
        probs = jax.nn.softmax(router(xt.astype(jnp.float32)), axis=-1)

        topk_probs, topk_idx = jax.lax.top_k(probs, kk)        # [T, K]
        masks = jax.nn.one_hot(topk_idx, e)                    # [T, K, E]
        gates = (
            topk_probs if kk == 1
            else topk_probs
            / jnp.sum(topk_probs, axis=-1, keepdims=True)
        )                                                      # [T, K]

        # Switch/GShard load-balance loss: E * sum(fraction * prob), with
        # the token fraction taken over FIRST choices (both papers').
        fraction = jnp.mean(masks[:, 0], axis=0)
        prob_mean = jnp.mean(probs, axis=0)
        self.sow(
            "losses", "moe_aux_loss",
            e * jnp.sum(fraction * prob_mean),
        )

        # Position of each token within its expert's capacity buffer,
        # priority-ordered: every first choice claims a slot before any
        # second choice (GShard's dispatch order); tokens past capacity
        # are dropped (residual passes them through).  K is static so
        # this unrolls into K cumsums.
        dispatch = jnp.zeros((tokens, e, capacity), jnp.float32)
        combine = jnp.zeros((tokens, e, capacity), jnp.float32)
        claimed = jnp.zeros((e,), jnp.float32)
        for sel in range(kk):
            mask_s = masks[:, sel]                              # [T, E]
            position = (
                jnp.cumsum(mask_s, axis=0) - 1.0 + claimed[None, :]
            ) * mask_s
            keep = (position < capacity) & (mask_s > 0)         # [T, E]
            onehot_pos = jax.nn.one_hot(
                jnp.clip(position, 0, capacity - 1).astype(jnp.int32),
                capacity,
            )                                                   # [T, E, C]
            slot = onehot_pos * keep[..., None]                 # [T, E, C]
            dispatch = dispatch + slot
            combine = combine + slot * gates[:, sel][:, None, None]
            claimed = claimed + jnp.sum(mask_s, axis=0)

        # Stacked expert weights, sharded over the expert mesh axis by the
        # EP_RULES PartitionSpecs (parallel/tp_rules.py).
        wi = self.param(
            "wi", nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, m, self.hidden_dim),
        )
        wo = self.param(
            "wo", nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, self.hidden_dim, m),
        )

        xin = jnp.einsum(
            "tec,tm->ecm", dispatch.astype(self.dtype), xt.astype(self.dtype)
        )                                                       # [E, C, M]
        h = self.activation(
            jnp.einsum("ecm,emh->ech", xin, wi.astype(self.dtype))
        )
        xout = jnp.einsum("ech,ehm->ecm", h, wo.astype(self.dtype))
        out = jnp.einsum(
            "tec,ecm->tm", combine.astype(self.dtype), xout
        )
        return out.reshape(b, s, m).astype(x.dtype)
