"""Config system.

The reference exposes a kwargs whitelist of exactly eleven keys enforced by
``validate_kwargs`` (ref: src/trainer.py:26-28, 307-311) with defaults
unpacked via ``config.get`` (ref: src/trainer.py:30-41).  This module keeps
that public surface — the same key names, defaults, and TypeError behaviour —
but expresses it as one dataclass so every component (CLI, notebooks,
Trainer) shares a single validated source of truth (the reference splits it
across argparse defaults, notebook hyperparameter dicts and the Trainer).

Deliberate divergences from the reference (documented, see SURVEY.md §5):

* ``backend`` names TPU-native communication stacks instead of torch process
  group backends.  The reference's names are accepted as aliases so the
  02-notebook hyperparameter dict keeps working: ``smddp``/``nccl`` (the GPU
  collectives, ref: main.py:72-73) map to ``tpu`` (XLA collectives over
  ICI/DCN) and ``gloo`` (the CPU fallback, ref: main.py:73) maps to ``cpu``
  (host-platform simulated mesh).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

# The exact whitelist from ref: src/trainer.py:26-27.
ALLOWED_KWARGS = {
    "seed",
    "scheduler",
    "optimizer",
    "momentum",
    "weight_decay",
    "lr",
    "criterion",
    "metric",
    "pred_function",
    "model_dir",
    "backend",
}

# Reference backend strings (ref: main.py:72-73) mapped to TPU-native stacks.
BACKEND_ALIASES = {
    "smddp": "tpu",
    "nccl": "tpu",
    "gloo": "cpu",
    "tpu": "tpu",
    "cpu": "cpu",
}


def validate_kwargs(
    kwargs: Dict[str, Any],
    allowed_kwargs,
    error_message: str = "Keyword argument not understood:",
) -> None:
    """Raise ``TypeError`` on unknown config keys (ref: src/trainer.py:307-311)."""
    for kwarg in kwargs:
        if kwarg not in allowed_kwargs:
            raise TypeError(error_message, kwarg)


@dataclasses.dataclass
class TrainerConfig:
    """Validated trainer config — same keys/defaults as ref: src/trainer.py:30-41."""

    seed: int = 32
    scheduler: Optional[str] = None
    optimizer: str = "sgd"
    momentum: float = 0.9
    weight_decay: float = 0.0
    lr: float = 0.001
    criterion: str = "cross_entropy"
    metric: Optional[str] = "accuracy"
    pred_function: Optional[str] = "softmax"
    model_dir: str = "model_output"
    backend: str = "tpu"

    @classmethod
    def from_kwargs(cls, **config: Any) -> "TrainerConfig":
        """Build from a reference-style config dict, rejecting unknown keys."""
        validate_kwargs(config, ALLOWED_KWARGS)
        out = cls(**config)
        try:
            out.backend = BACKEND_ALIASES[out.backend]
        except KeyError:
            raise ValueError(
                f"Unknown backend {out.backend!r}; expected one of "
                f"{sorted(set(BACKEND_ALIASES))}"
            ) from None
        return out

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)
