"""Paged, refcounted LoRA adapter pool — KVPagePool's design applied to
adapters.

Serving thousands of fine-tuned variants on one base model
(docs/serving.md "Batched LoRA adapters") needs adapter weights IN
device memory next to the base params, gathered per batch row inside
the one compiled decode program.  Like the KV pool:

* **Fixed-size slots.**  The device side (owned by the engine) is one
  stack per targeted projection per layer — ``A [S, in, rank]`` /
  ``B [S, rank, out]`` in the model's ``"lora"`` collection — where
  ``S = slots``.  Slot 0 is the TRASH adapter: all-zero, permanently
  pinned, what every row with no adapter reads — its delta is an exact
  float zero, so base traffic through a LoRA-enabled engine stays
  bit-identical to a LoRA-free engine.
* **One rank bucket.**  Every adapter's A/B is zero-padded to the
  pool's ``rank`` at upload (zero rows/columns contribute nothing), and
  the ``alpha/rank_trained`` scale is folded into B — so mixed-rank
  adapters share ONE static-shaped program and hot-load/swap never
  recompiles (compile_watch-pinned).
* **Host-owned index tables.**  Which slot holds which adapter is a
  host decision (this module); the compiled program just reads the
  per-row ``adapter_idx`` vector and the stacks as ordinary inputs.
* **Refcount / LRU eviction.**  A slot's count is the number of active
  requests decoding with it.  Eviction (to load a new adapter into a
  full pool) takes the least-recently-used slot with refcount 0 —
  a slot some request is decoding with can never be evicted out from
  under it.  When every slot is held, :class:`AdapterPoolExhausted`
  names the adapter that could not load.  Registered artifacts keep a
  host copy, so an evicted adapter reloads on demand.

Host-only module (numpy + stdlib): the engine owns every device
interaction, including the one compiled upload program that scatters a
prepared A/B set into a slot's stack rows.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ml_trainer_tpu.lora import load_lora_artifact

# Refcount sentinel pinning the trash slot 0: never allocated, never
# evicted (the KVPagePool idiom).
_TRASH_PIN = 1 << 30


class AdapterPoolExhausted(RuntimeError):
    """Every adapter slot is held by an active request; the named
    adapter cannot load until one releases.  The engine turns this into
    a structured client error (never a hang)."""


class UnknownAdapter(RuntimeError):
    """A request named an adapter nobody registered (hot-load it first
    via ``Server.load_adapter`` or the ``adapters=`` config)."""


@dataclasses.dataclass(frozen=True)
class AdapterConfig:
    """``Server(adapters=...)`` — the serving pool's geometry.

    ``slots``: concurrent resident adapters INCLUDING the trash slot 0
    (so ``slots - 1`` loadable adapters).  ``rank``: the pool's rank
    bucket — every adapter pads up to it (an adapter trained at a
    higher rank is refused at registration).  ``targets``: which Dense
    projections the pool stacks cover; adapters may target a subset
    (missing targets upload as zeros).  ``sources`` optionally
    preregisters artifacts (name -> path/bytes) at server construction.
    """

    slots: int = 9
    rank: int = 8
    targets: Tuple[str, ...] = ("qkv", "proj")
    sources: Optional[Dict[str, object]] = None

    def __post_init__(self):
        from ml_trainer_tpu.models.layers import LORA_TARGETS

        if self.slots < 2:
            raise ValueError(
                f"adapter slots must be >= 2 (slot 0 is the trash "
                f"adapter), got {self.slots}"
            )
        if self.rank < 1:
            raise ValueError(f"adapter rank must be >= 1, got {self.rank}")
        targets = tuple(self.targets)
        bad = [t for t in targets if t not in LORA_TARGETS]
        if not targets or bad:
            raise ValueError(
                f"adapter targets must be a non-empty subset of "
                f"{LORA_TARGETS}, got {self.targets!r}"
            )
        object.__setattr__(self, "targets", targets)


class AdapterPool:
    """Host-side slot allocator + adapter registry (thread-safe: the
    engine loop acquires/releases, any thread may register a hot-load).
    """

    def __init__(self, config: AdapterConfig):
        self.config = config
        self.slots = int(config.slots)
        self.rank = int(config.rank)
        self.targets = tuple(config.targets)
        self._lock = threading.Lock()
        # Registered artifacts: host copies (meta, {param_path: array})
        # — what makes eviction safe (reload on demand) and migration
        # possible (any replica sharing the registry can bind).
        self._registry: Dict[str, tuple] = {}
        self._slot_of: Dict[str, int] = {}
        self._name_of: Dict[int, str] = {}
        self.refcount = np.zeros(self.slots, np.int64)
        self.refcount[0] = _TRASH_PIN
        self._free: collections.deque = collections.deque(
            range(1, self.slots)
        )
        self._clock = itertools.count(1)
        self._last_used = np.zeros(self.slots, np.int64)
        # Counters feeding serving_adapter_{hits,loads,evictions}_total.
        self.hits = 0
        self.loads = 0
        self.evictions = 0
        for name, src in sorted((config.sources or {}).items()):
            self.register(name, src)

    # -- registry ---------------------------------------------------------

    def register(self, name: str, source) -> dict:
        """Register an adapter artifact under ``name`` (hot-load
        surface; thread-safe, idempotent re-register replaces — the NEXT
        acquire of an unheld adapter sees the new weights).  Returns the
        artifact meta.  Raises ``ValueError`` when the artifact's rank
        exceeds the pool bucket or targets fall outside the pool's."""
        if not isinstance(name, str) or not name:
            raise ValueError(f"adapter name must be a non-empty string, "
                             f"got {name!r}")
        meta, leaves = load_lora_artifact(source)
        rank = int(meta["rank"])
        if rank > self.rank:
            raise ValueError(
                f"adapter '{name}' rank {rank} exceeds the pool's rank "
                f"bucket {self.rank} — size the pool for your largest "
                "adapter"
            )
        extra = [t for t in meta.get("targets", []) if t not in self.targets]
        if extra:
            raise ValueError(
                f"adapter '{name}' targets {extra} not covered by the "
                f"pool's targets {self.targets}"
            )
        with self._lock:
            replacing = name in self._slot_of
            self._registry[name] = (meta, leaves)
            if replacing:
                # Re-register of a RESIDENT adapter: drop the stale slot
                # (refused while held — the running stream keeps the
                # weights it started with).
                slot = self._slot_of[name]
                if self.refcount[slot] == 0:
                    self._evict_slot(slot)
        return meta

    def registered(self) -> List[str]:
        with self._lock:
            return sorted(self._registry)

    def resident(self) -> List[str]:
        """Adapters currently holding a device slot (the ``/healthz``
        ``adapters_resident`` payload the router's affinity reads)."""
        with self._lock:
            return sorted(self._slot_of)

    def artifact(self, name: str) -> Optional[tuple]:
        with self._lock:
            return self._registry.get(name)

    # -- slot lifecycle ---------------------------------------------------

    def _evict_slot(self, slot: int) -> None:
        # Caller holds the lock.
        name = self._name_of.pop(slot)
        del self._slot_of[name]
        self._free.append(slot)

    def acquire(self, name: str) -> Tuple[int, Optional[tuple]]:
        """Pin ``name`` for one request: returns ``(slot, upload)``
        where ``upload`` is None on a residency hit, else the
        ``(meta, leaves)`` the engine must upload into ``slot`` before
        the next dispatch.  Refcounts the slot either way; raises
        :class:`UnknownAdapter` / :class:`AdapterPoolExhausted`
        (naming the adapter) instead of blocking."""
        with self._lock:
            art = self._registry.get(name)
            if art is None:
                raise UnknownAdapter(
                    f"unknown adapter '{name}': not registered on this "
                    f"server (registered: {sorted(self._registry) or '[]'})"
                )
            slot = self._slot_of.get(name)
            if slot is not None:
                self.refcount[slot] += 1
                self._last_used[slot] = next(self._clock)
                self.hits += 1
                return slot, None
            if not self._free:
                # LRU among refcount-0 residents; held slots are never
                # evicted (the running streams own their weights).
                candidates = [
                    s for s in self._name_of if self.refcount[s] == 0
                ]
                if not candidates:
                    raise AdapterPoolExhausted(
                        f"adapter pool exhausted loading '{name}': all "
                        f"{self.slots - 1} slot(s) held by active "
                        "requests; retry when one finishes or size the "
                        "pool up (AdapterConfig.slots)"
                    )
                victim = min(candidates, key=lambda s: self._last_used[s])
                self._evict_slot(victim)
                self.evictions += 1
            slot = self._free.popleft()
            self._slot_of[name] = slot
            self._name_of[slot] = name
            self.refcount[slot] = 1
            self._last_used[slot] = next(self._clock)
            self.loads += 1
            return slot, art

    def release(self, slot: int) -> None:
        """Drop one request's pin on ``slot`` (trash slot 0 is a no-op —
        base-model rows).  The adapter STAYS resident at refcount 0
        (warm for the next request) until eviction needs the slot."""
        if slot == 0:
            return
        with self._lock:
            if self.refcount[slot] <= 0:
                raise ValueError(f"release of unheld adapter slot {slot}")
            self.refcount[slot] -= 1

    def slot_name(self, slot: int) -> Optional[str]:
        with self._lock:
            return self._name_of.get(slot)

    def free_count(self) -> int:
        """Slots holding no adapter at all (evictable refcount-0
        residents are NOT counted free — they are warm cache)."""
        with self._lock:
            return len(self._free)

    def used_count(self) -> int:
        with self._lock:
            return len(self._name_of)

    def counters(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "loads": self.loads,
                "evictions": self.evictions,
            }


def prepare_upload(meta: dict, leaves: Dict[str, np.ndarray],
                   stack_shapes: Dict[str, tuple],
                   rank: int) -> Dict[str, np.ndarray]:
    """Shape one artifact for the pool's rank bucket: for every stack
    leaf path (``block0/attn/qkv_lora_A`` style, stack shape
    ``[S, in, rank]`` / ``[S, rank, out]``) produce the ``[in, rank]``
    / ``[rank, out]`` row to scatter into the slot —

    * A pads ``[in, r_trained] -> [in, rank]`` with zero columns;
    * B pads ``[r_trained, out] -> [rank, out]`` with zero rows AND
      folds the ``alpha/r_trained`` scale in (zero-padding is exact:
      padded rank components contribute 0 to xAB);
    * targets the adapter does not carry upload as zeros (base
      behavior for that projection).

    Pure host numpy — the engine casts to the stack dtype and runs the
    one compiled scatter."""
    r_trained = int(meta["rank"])
    scale = float(meta["alpha"]) / r_trained
    out: Dict[str, np.ndarray] = {}
    for path, shape in stack_shapes.items():
        want = tuple(shape[1:])                     # drop the slot dim
        src = leaves.get(path)
        if src is None:
            out[path] = np.zeros(want, np.float32)
            continue
        src = np.asarray(src, np.float32)
        if path.endswith("_lora_A"):
            if src.shape[0] != want[0] or src.shape[1] > rank:
                raise ValueError(
                    f"adapter leaf '{path}' shape {src.shape} does not "
                    f"fit stack row {want}"
                )
            row = np.zeros(want, np.float32)
            row[:, : src.shape[1]] = src
        else:
            if src.shape[1] != want[1] or src.shape[0] > rank:
                raise ValueError(
                    f"adapter leaf '{path}' shape {src.shape} does not "
                    f"fit stack row {want}"
                )
            row = np.zeros(want, np.float32)
            row[: src.shape[0], :] = src * scale
        out[path] = row
    return out
