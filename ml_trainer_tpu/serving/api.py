"""Thread-safe serving front end over the slot decode engine.

``Server`` owns the engine, the FIFO scheduler, and one worker thread
driving the admit/step loop.  Callers interact through:

* ``submit(prompt, max_new_tokens, ...) -> TokenStream`` — non-blocking;
  the stream iterates tokens as they decode and ``result()`` blocks for
  the full sequence (prompt + continuation, ``generate()``'s layout);
* ``complete(...)`` — the blocking convenience wrapper;
* ``serve_http(port=...)`` — an OPTIONAL stdlib HTTP front end
  (``http.server``; no dependencies), started only when asked for
  (constructor flag ``http_port`` or an explicit call): POST
  ``/v1/generate`` with ``{"prompt": [ids...], "max_new_tokens": n,
  "temperature": t?, "seed": s?, "eos_token_id": e?, "deadline": d?,
  "tenant": name?, "priority": p?}``
  returns ``{"tokens": [...]}``; GET ``/metrics`` serves Prometheus
  text exposition of the process telemetry registry (serving gauges,
  lifecycle latency histograms and SLO attainment freshly published —
  what a scraper points at); GET ``/metrics.json``
  keeps the flat JSON snapshot shape; GET ``/slo`` the structured SLO
  attainment snapshot; GET ``/healthz`` liveness/health
  (503 when wedged or draining); POST ``/admin/profile``
  ``{"steps": K, "logdir"?: ...}`` arms an on-demand ``jax.profiler``
  window over the next K decode steps (telemetry/spans.py).
  Backpressure maps to HTTP 429, deadlines to 504.

Multi-replica surface (serving/router.py, docs/serving.md
"Disaggregated serving"): ``role`` labels the replica for the router
(advertised on ``/healthz`` with queue depth, free KV pages and active
slots — the placement signals), ``submit_request`` enqueues a
pre-built request (resume prefixes, migration sinks), and ``adopt``
accepts a KV migration exported by another replica's prefill
(serving/transfer.py) — imported bit-for-bit into a free slot by the
loop thread, falling back to requeue-and-reprefill under page
pressure.

Failure contract (docs/resilience.md): clients NEVER hang on a dead
engine.  A watchdog thread monitors the loop's heartbeat; a decode step
that wedges past ``watchdog_timeout`` (or an engine thread that dies)
fails every in-flight and queued request with a structured error,
marks the server unhealthy (``/healthz`` -> 503) and refuses new
admissions.  ``drain()`` is the graceful counterpart: stop admission,
finish what's in flight, then ``close()``.
"""

from __future__ import annotations

import base64
import collections
import json
import os
import queue
import threading
import time
from typing import Dict, Optional

import numpy as np

from ml_trainer_tpu.serving.engine import SlotDecodeEngine
from ml_trainer_tpu.serving.metrics import ServingMetrics
from ml_trainer_tpu.serving.overload import DegradationConfig, OverloadShed
from ml_trainer_tpu.serving.scheduler import (
    AdmissionError,
    DeadlineExceeded,
    EngineUnhealthy,
    Request,
    TenantScheduler,
    _DONE,
)
from ml_trainer_tpu.serving.slo import SloPolicy, SloTracker
from ml_trainer_tpu.telemetry import compile_watch, spans
from ml_trainer_tpu.telemetry.flight import get_recorder
from ml_trainer_tpu.utils.logging import get_logger

# Stream sentinel kind a migration sink pushes between tokens — the
# SAME literal serving/router.py's ``_MIGRATE`` uses (api.py must not
# import router; the string is the wire contract).  The fleet stream
# endpoint turns it into an ``{"m": <payload>}`` NDJSON line.
_KV_MIGRATE = "__kv_migrate__"

# Cross-process trace context rides the fleet RPCs as this header (a
# JSON object: trace_id / parent / origin_pid).  The wire meta carries
# the same dict inline for /v1/stream and /v1/adopt; the header is the
# fallback for clients that speak plain /v1/generate.
TRACE_HEADER = "X-Trace-Context"


def _trace_ctx_header(headers) -> Optional[dict]:
    """Parse ``X-Trace-Context`` into a trace-ctx dict (None when
    absent or malformed — a bad trace header must never fail a
    request)."""
    raw = headers.get(TRACE_HEADER, "")
    if not raw:
        return None
    try:
        ctx = json.loads(raw)
    except (TypeError, ValueError):
        return None
    return ctx if isinstance(ctx, dict) and ctx else None


class TokenStream:
    """Streaming view of one request: iterate tokens as they arrive, or
    ``result()`` for the whole sequence."""

    def __init__(self, req: Request, prompt: np.ndarray):
        self._req = req
        self._prompt = prompt
        self._drained = False

    @property
    def request(self) -> Request:
        return self._req

    def __iter__(self):
        while True:
            item = self._req._stream.get()
            if item == _DONE:
                self._drained = True
                self._raise_on_failure()
                return
            yield item

    def _raise_on_failure(self):
        if self._req.state == "expired":
            raise DeadlineExceeded(self._req.error or "deadline exceeded")
        if self._req.state == "shed":
            raise OverloadShed(
                self._req.error or "request shed under overload",
                retry_after=self._req.retry_after,
            )
        if self._req.state == "error":
            raise RuntimeError(self._req.error or "serving engine error")

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until the request finishes; returns
        ``[prompt + new tokens]`` (1-D int32).  Raises
        ``DeadlineExceeded`` / ``RuntimeError`` on failure states, and
        ``TimeoutError`` when ``timeout`` expires with the request still
        unfinished — including when the engine is wedged or dead, so a
        blocking caller always gets control back."""
        import queue as _q

        if not self._drained:
            deadline = (
                time.monotonic() + timeout if timeout is not None else None
            )
            while True:
                left = None
                if deadline is not None:
                    left = max(deadline - time.monotonic(), 1e-3)
                try:
                    item = self._req._stream.get(timeout=left)
                except _q.Empty:
                    raise TimeoutError(
                        f"request {self._req.id} not finished within "
                        f"{timeout}s ({len(self._req.tokens)} token(s) so "
                        "far; engine may be wedged — see Server.health())"
                    ) from None
                if item == _DONE:
                    self._drained = True
                    break
        self._raise_on_failure()
        return np.concatenate(
            [self._prompt, np.asarray(self._req.tokens, np.int32)]
        )

    @property
    def tokens(self) -> list:
        """Tokens decoded so far (no blocking)."""
        return list(self._req.tokens)


class Server:
    """Continuous-batching serving session: engine + scheduler + one
    worker thread.  Use as a context manager in tests/scripts so the
    thread is joined deterministically."""

    def __init__(self, model, variables: dict, max_batch: int = 8,
                 max_queue: int = 64,
                 metrics: Optional[ServingMetrics] = None,
                 idle_poll: float = 0.02,
                 http_port: Optional[int] = None,
                 spec_k: int = 0, drafter="ngram",
                 draft_variables: Optional[dict] = None,
                 watchdog_timeout: Optional[float] = 60.0,
                 kv_page_size: int = 0, kv_pages: int = 0,
                 paged_kernel: bool = False,
                 quant_int8: bool = False,
                 prefix_cache: bool = True,
                 prefix_scope: str = "tenant",
                 tenants: Optional[dict] = None,
                 max_preemptions: int = 8,
                 slo: Optional[SloPolicy] = None,
                 slo_timelines: int = 64,
                 role: str = "both",
                 adapters=None,
                 prefill_chunk: int = 0):
        """``watchdog_timeout``: seconds the engine loop may go without a
        heartbeat WHILE work is pending before the watchdog declares it
        wedged — fails every in-flight/queued request with a structured
        error, marks the server unhealthy and stops admission.  Size it
        well above the slowest single decode/prefill dispatch (first-hit
        XLA compiles run on this thread).  ``None`` disables the
        watchdog.

        ``kv_page_size > 0`` switches the engine to the PAGED KV cache
        (docs/serving.md): K/V lives in ``kv_pages`` fixed-size pages
        (0 = full contiguous capacity, i.e. no oversubscription) behind
        per-slot page tables; ``prefix_cache`` enables the radix prefix
        cache so shared prompt prefixes skip prefill; under page
        pressure long generations are preempted and re-queued (at most
        ``max_preemptions`` times each) with their generated tokens as
        a resumable prefix.  ``prefix_scope`` controls prefix sharing:
        ``"tenant"`` (default) keeps each tenant's cached blocks in its
        own namespace — cache residency is observable via TTFT and the
        hit-rate metrics, so a shared trie is a cross-tenant content
        side channel; ``"global"`` opts a trusted single-team
        deployment back into cross-tenant sharing.

        ``tenants`` maps tenant name -> :class:`TenantConfig` (weight,
        max_active, max_queued); requests name their tenant at
        ``submit``.  Unknown tenants get the default config.

        ``slo`` sets the :class:`SloPolicy` (TTFT/TPOT budgets + target)
        the always-on :class:`SloTracker` judges finished requests
        against (``server.slo`` — attainment/burn-rate on ``/metrics``
        and the ``/slo`` endpoint); ``slo_timelines`` bounds the
        last-N request-timeline ring attached to flight dumps.

        ``role`` labels this replica for the disaggregated router
        (serving/router.py): ``"prefill"``, ``"decode"`` or ``"both"``
        (the default — a standalone server serves everything).  The
        role is advertised on ``/healthz`` and is ROUTING POLICY only;
        the engine itself can always do both.

        ``adapters`` (docs/serving.md "Batched LoRA adapters"): an
        :class:`~ml_trainer_tpu.serving.adapter_pool.AdapterConfig`
        arming the batched-LoRA pool — requests then name an adapter at
        ``submit(adapter=...)`` (HTTP ``"adapter"``), each batch row
        gathers its own low-rank delta inside the one compiled decode
        program, and ``load_adapter`` hot-loads new artifacts under
        live traffic with zero recompiles.  ``adapter=None`` traffic
        reads the all-zero trash slot and stays byte-identical to an
        adapter-free server.

        ``prefill_chunk > 0`` (page multiple; needs paged KV) arms
        CHUNKED PREFILL: a prompt longer than the chunk admits through
        page-aligned continuation windows with decode ticks interleaved
        between windows, so one long prompt cannot head-of-line-block
        every short request's TTFT (docs/serving.md).

        ``paged_kernel`` (needs paged KV) runs the S == 1 decode step
        through the fused Pallas paged-attention kernel
        (ops/kernels/paged_attention.py; docs/kernels.md) — the
        page-table gather streams HBM->VMEM inside the kernel instead
        of materializing [B, H, L, D] twice per step.  Off-TPU the knob
        dispatches to the lax reference, which IS the gather path, so
        outputs stay byte-identical.

        ``quant_int8`` serves the decode step with int8-quantized
        qkv/proj/fc_in/fc_out weights + per-column scales
        (ops/kernels/int8_matmul.py; prefill stays fp32).  Opt-in and
        quality-gated (argmax agreement vs fp32 on the bench leg), NOT
        bit-identical to fp32; refused with ``spec_k > 0`` or
        ``adapters``."""
        if role not in ("prefill", "decode", "both"):
            raise ValueError(
                f"role must be 'prefill', 'decode' or 'both', got {role!r}"
            )
        self.role = role
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self.slo = SloTracker(
            policy=slo, metrics=self.metrics, keep_timelines=slo_timelines,
        )
        self.engine = SlotDecodeEngine(
            model, variables, max_batch=max_batch, metrics=self.metrics,
            spec_k=spec_k, drafter=drafter, draft_variables=draft_variables,
            kv_page_size=kv_page_size, kv_pages=kv_pages,
            paged_kernel=paged_kernel, quant_int8=quant_int8,
            prefix_cache=prefix_cache, prefix_scope=prefix_scope,
            max_preemptions=max_preemptions, adapters=adapters,
            prefill_chunk=prefill_chunk,
        )
        self.scheduler = TenantScheduler(
            max_batch, max_queue=max_queue, metrics=self.metrics,
            tenants=tenants,
        )
        # Every flight dump (watchdog trip, engine death, preemption
        # storm) carries the last-N finished request timelines plus the
        # in-flight ones — the dump names the requests it hurt.
        self.engine._flight.register_context_provider(
            "serving_requests", self.slo.context_payload
        )
        # Watchtower TSDB (telemetry/watchtower.py): history behind this
        # process's registry, sampled on every /metrics publish — the
        # router's federation scrape doubles as the sampler — and served
        # as sparklines on GET /dash.  Pure host work: no device calls,
        # no compiled programs.
        from ml_trainer_tpu.telemetry.watchtower import (
            TimeSeriesStore, watch_context,
        )

        self.watchtower = TimeSeriesStore()
        self.engine._flight.register_context_provider(
            "watchtower", lambda: watch_context(self.watchtower)
        )
        self._idle_poll = idle_poll
        self._log = get_logger("ml_trainer_tpu.serving")
        self._wake = threading.Event()
        self._stopping = False
        self._draining = False
        self.healthy = True
        self._unhealthy_reason: Optional[str] = None
        self._health_lock = threading.Lock()
        self._last_beat = time.monotonic()
        self._admitting_req: Optional[Request] = None
        # KV adoptions landing from another replica's prefill (the
        # router's migration hand-off): (request, KVSlotExport) pairs
        # drained by the loop thread.  Plain deque — single consumer
        # (the loop), producers only append; both ends are atomic.
        self._adoptions: collections.deque = collections.deque()
        # Overload control (serving/overload.py): the active
        # degradation-ladder rung + config mirror the ladder applies;
        # level 0 is full service.
        self._degradation_level = 0
        self._degradation_cfg: Optional[DegradationConfig] = None
        # Router plumbing: the fleet index (chaos faults name replicas
        # by it), the slow-down latch the replica_slow fault arms, and
        # the evacuation sink a role reassignment installs (the loop
        # thread exports every active slot's KV through it).
        self.replica_index = 0
        self._slow_until = 0.0
        self._busy_iters = 0
        self._evacuate_sink = None
        self._evacuated = threading.Event()
        self._httpd = None
        self._http_thread = None
        # Fleet identity (serving/fleet.py): process birth time for
        # ``uptime_s``, and the transport this server is reached over —
        # "inproc" (a Python object in the caller's process) until the
        # fleet worker flips it to "http".
        self._started_at = time.monotonic()
        self.transport = "inproc"
        # Fleet-assigned replica name ("p0", "d1", ...): stamped by the
        # fleet worker main so trace lanes, stream-accept lines and
        # incident bundle entries attribute to the replica, not a pid.
        self.name = ""
        # Wire-id -> Request registry for the fleet stream endpoints
        # (/v1/stream, /v1/adopt): lets /v1/cancel reach a stream by the
        # ROUTER's id, which is stable across processes.
        self._wire_streams: Dict[int, Request] = {}
        self._wire_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="serving-engine"
        )
        self._thread.start()
        self._watchdog_timeout = watchdog_timeout
        self._watchdog_thread = None
        if watchdog_timeout is not None:
            if watchdog_timeout <= 0:
                raise ValueError(
                    f"watchdog_timeout must be positive or None, got "
                    f"{watchdog_timeout}"
                )
            self._watchdog_thread = threading.Thread(
                target=self._watchdog, daemon=True, name="serving-watchdog"
            )
            self._watchdog_thread.start()
        if http_port is not None:
            self.serve_http(port=http_port)

    # -- client surface --------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0, rng=None,
               eos_token_id: Optional[int] = None,
               deadline: Optional[float] = None,
               tenant: str = "default", priority: int = 0,
               adapter: Optional[str] = None,
               trace: Optional[dict] = None) -> TokenStream:
        """Enqueue one request (thread-safe).  Raises ``AdmissionError``
        when the queue (global or the tenant's) is at its watermark (or
        the server is draining), ``EngineUnhealthy`` when the engine is
        wedged/dead, and ``ValueError`` on a request the engine could
        never serve.  ``tenant``/``priority`` feed the multi-tenant
        scheduler (higher priority admits first within a tenant);
        ``adapter`` names the LoRA adapter to decode with (needs
        ``Server(adapters=...)``; None = the base model)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("prompt must be a non-empty 1-D token array")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if prompt.size + max_new_tokens + self.engine.spec_k > \
                self.engine.max_len:
            extra = (
                f" + spec_k ({self.engine.spec_k}) — the speculative "
                "verify window needs spec_k tokens of cache slack"
                if self.engine.spec_k else ""
            )
            raise ValueError(
                f"prompt ({prompt.size}) + new tokens ({max_new_tokens})"
                f"{extra} exceeds the model's max_len "
                f"({self.engine.max_len})"
            )
        if eos_token_id is not None and not (
            0 <= eos_token_id < self.engine.vocab_size
        ):
            raise ValueError(
                f"eos_token_id must be in [0, {self.engine.vocab_size}), "
                f"got {eos_token_id}"
            )
        if not isinstance(tenant, str) or not tenant:
            raise ValueError(f"tenant must be a non-empty string, got "
                             f"{tenant!r}")
        if adapter is not None:
            if not isinstance(adapter, str) or not adapter:
                raise ValueError(
                    f"adapter must be a non-empty string or None, got "
                    f"{adapter!r}"
                )
            if self.engine.adapters is None:
                raise ValueError(
                    f"request names adapter '{adapter}' but this server "
                    "has no adapter pool (construct with "
                    "Server(adapters=AdapterConfig(...)))"
                )
        req = Request(
            prompt=prompt, max_new_tokens=int(max_new_tokens),
            temperature=float(temperature), rng=rng,
            eos_token_id=eos_token_id, deadline=deadline,
            tenant=tenant, priority=int(priority), adapter=adapter,
        )
        if trace:
            req.trace_ctx = dict(trace)
        self.submit_request(req)
        return TokenStream(req, prompt)

    def load_adapter(self, name: str, source) -> dict:
        """Hot-load (or replace) a LoRA adapter artifact under live
        traffic (thread-safe).  Registration is host-only — the device
        upload runs in the engine loop at the adapter's next admission
        through the one warm compiled scatter, so a hot-load mints no
        compiles and never stalls running streams.  Returns the
        artifact meta.  Raises ``ValueError`` when the pool is absent
        or the artifact does not fit its rank bucket/targets."""
        if self.engine.adapters is None:
            raise ValueError(
                "this server has no adapter pool (construct with "
                "Server(adapters=AdapterConfig(...)))"
            )
        return self.engine.adapters.register(name, source)

    def submit_request(self, req: Request) -> None:
        """Enqueue a pre-built :class:`Request` (thread-safe) — the
        router's shadow-submission surface: the request may carry
        committed ``tokens`` (a resume / redistribution continues from
        them as a prefix) and a ``migration_sink`` (prefill-and-export
        instead of decoding in place).  The caller validated the
        request shape; this enforces only server state."""
        if self._stopping:
            raise RuntimeError("server is closed")
        if not self.healthy:
            raise EngineUnhealthy(
                self._unhealthy_reason or "serving engine unhealthy"
            )
        if self._draining:
            raise AdmissionError(
                "server is draining: admission stopped, in-flight "
                "requests are finishing"
            )
        # Degradation rungs act at SUBMISSION only (serving/overload.py):
        # a request already carrying committed tokens is a resume /
        # redistribution of a running stream and is never clamped or
        # shed — the byte-identity contract.
        level, cfg = self._degradation_level, self._degradation_cfg
        if level and cfg is not None and not req.tokens:
            if level >= 4 and req.priority < cfg.shed_below_priority:
                self.metrics.record_shed(req.tenant)
                raise OverloadShed(
                    f"request {req.id} (tenant '{req.tenant}', priority "
                    f"{req.priority}) shed at admission: degradation "
                    f"rung shed_queued rejects priority < "
                    f"{cfg.shed_below_priority}; retry after "
                    f"{cfg.retry_after_s}s",
                    retry_after=cfg.retry_after_s,
                )
            if req.max_new_tokens > cfg.clamp_tokens:
                req.max_new_tokens = cfg.clamp_tokens
                req.mark(
                    "degraded_clamp", level=level,
                    clamp=cfg.clamp_tokens,
                )
        # Observer installed BEFORE the enqueue so every terminal path —
        # including queued-expiry inside the scheduler — lands in the
        # SLO accounting; a rejected submit never enqueues, so its
        # observer simply never fires.
        req.observer = self.slo.observe
        self.scheduler.submit(req)
        self.slo.track(req)
        self._wake.set()

    def adopt(self, req: Request, export, resolver=None) -> None:
        """Accept a KV migration (thread-safe): ``req`` was prefilled on
        another replica and ``export`` is its slot's page payload
        (serving/transfer.py).  The loop thread imports it into a free
        slot bit-for-bit and decodes from there; if the pool cannot
        hold the chain the request falls back to requeue-and-reprefill
        from its committed tokens.  Raises ``EngineUnhealthy`` /
        ``RuntimeError`` when this replica cannot take work.

        ``resolver`` (fleet RPC, serving/fleet.py): a
        ``callable(status, detail)`` the loop thread invokes with the
        import outcome — ``"adopted"``, ``"corrupt"``, ``"no_memory"``,
        ``"error"``, ``"expired"``, ``"cancelled"``, ``"draining"`` or
        ``"unhealthy"``.  With a resolver installed, corrupt/no_memory
        outcomes are REPORTED instead of locally requeued: the remote
        router holds the payload and falls back to its next candidate
        (the cross-process twin of the in-process fallback loop)."""
        if self._stopping:
            raise RuntimeError("server is closed")
        if not self.healthy:
            raise EngineUnhealthy(
                self._unhealthy_reason or "serving engine unhealthy"
            )
        if self._draining:
            raise AdmissionError(
                "server is draining: admission stopped, in-flight "
                "requests are finishing"
            )
        # This replica's tracker owns the request's lifecycle from here
        # (the prefill replica forgot it at export).
        req.observer = self.slo.observe
        self.slo.track(req)
        self._adoptions.append((req, export, resolver))
        self._wake.set()

    def complete(self, prompt, max_new_tokens: int,
                 timeout: Optional[float] = None, **kwargs) -> np.ndarray:
        """Blocking one-shot: submit and wait for the full sequence."""
        return self.submit(prompt, max_new_tokens, **kwargs).result(
            timeout=timeout
        )

    # -- overload control (serving/overload.py) ---------------------------

    def set_degradation(self, level: int,
                        config: Optional[DegradationConfig] = None) -> None:
        """Apply a degradation-ladder rung (thread-safe, idempotent):
        0 full service, 1 clamp fresh token budgets, 2 speculative
        decode off, 3 prefix-cache hits only, 4 shed low-priority.
        Effects hit NEW admissions only; running streams finish
        undegraded (tests/test_overload.py pins the byte identity)."""
        cfg = config if config is not None else DegradationConfig()
        self._degradation_cfg = cfg
        self._degradation_level = int(level)
        eng = self.engine
        eng.degradation_level = int(level)
        eng.shed_retry_after = cfg.retry_after_s
        eng.spec_enabled = int(level) < 2

    def shed_queued(self, below_priority: int, retry_after: float,
                    cause: str = "overload") -> int:
        """Shed this server's queued requests below ``below_priority``
        (the ladder's rung-4 entry action); returns the count."""
        return self.scheduler.shed_queued(
            below_priority, retry_after, cause=cause
        )

    def cancel(self, req: Request) -> None:
        """Withdraw a request this server no longer needs to serve (the
        hedging loser, serving/router.py): the SLO tracker forgets it
        (a cancelled duplicate is not an SLO miss), the observer is
        cleared, and the loop thread drops it at the next boundary —
        queued entries never admit, active slots release with their
        pages donated."""
        self.slo.forget(req)
        req.observer = None
        req.cancel_requested = True
        self._wake.set()

    def evacuate(self, sink, timeout: float = 30.0) -> bool:
        """Drain this replica THROUGH the migration machinery (role
        reassignment, serving/autoscaler.py): the loop thread exports
        every active slot's KV and hands ``(request, export)`` to
        ``sink`` — the router adopts each onto another replica, so the
        streams keep flowing with their pages instead of re-prefilling —
        and every queued request fails with a retryable ``draining``
        error the router redistributes.  Blocks (up to ``timeout``)
        until the loop thread finished the sweep; returns True when it
        did.  The server stays healthy and keeps serving afterwards —
        the caller controls placement."""
        if not self.engine.paged:
            raise ValueError(
                "evacuate needs a paged engine: the page chain is the "
                "migration unit (kv_page_size > 0)"
            )
        self._evacuated.clear()
        self._evacuate_sink = sink
        self._wake.set()
        return self._evacuated.wait(timeout=timeout)

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful drain: stop admission (``submit`` raises
        ``AdmissionError``) and block until every queued + in-flight
        request finishes, or ``timeout`` passes, or the engine goes
        unhealthy.  Returns True when fully drained.  The usual shutdown
        sequence is ``drain(); close()``."""
        self._draining = True
        deadline = time.monotonic() + timeout if timeout is not None else None
        while self.healthy and not self._stopping:
            if (
                self.engine.active_count() == 0
                and self.scheduler.queue_depth() == 0
            ):
                return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(min(self._idle_poll, 0.05))
        return (
            self.engine.active_count() == 0
            and self.scheduler.queue_depth() == 0
        )

    def health(self) -> dict:
        """Structured health snapshot (the ``/healthz`` payload).  The
        router places requests on these fields — ``role``,
        ``queue_depth``, ``kv_pages_free``, ``active_slots`` — instead
        of round-robin; the shape is pinned by a golden test in
        tests/test_serving.py."""
        from ml_trainer_tpu.resilience.faults import active_plan

        plan = active_plan()
        if plan is not None:
            # healthz_flap chaos: ONE poll looks dropped (the payload
            # says why) — the router's flap damping must absorb it
            # without a spurious drain-and-redistribute.
            fault = plan.fire("healthz_flap", host=self.replica_index)
            if fault is not None:
                return {
                    "ok": False, "healthy": False, "draining": False,
                    "closed": False, "flap": True,
                    "reason": "injected healthz flap (transient)",
                }
        engine = self.engine
        return {
            "ok": self.healthy and not self._draining and not self._stopping,
            "healthy": self.healthy,
            "draining": self._draining,
            "closed": self._stopping,
            "reason": self._unhealthy_reason,
            "role": self.role,
            # Process identity (fleet debugging, serving/fleet.py): which
            # OS process answered, how long it has been up, and whether
            # it is reached in-process or over a socket.
            "pid": os.getpid(),
            "uptime_s": round(time.monotonic() - self._started_at, 3),
            "transport": self.transport,
            # Fleet observability plane (serving/router.py): per-replica
            # recompile budget surfaced through the router's aggregated
            # /healthz, and the clock handshake the router uses to align
            # this process's trace lane (trace_now_us sampled while the
            # router brackets the poll with its own clock).
            "compile_events_post_warmup_total": (
                compile_watch.post_warmup_count()
                if compile_watch.installed() else None
            ),
            **spans.clock_payload(),
            "active_requests": engine.active_count() + engine.chunking_count(),
            "active_slots": engine.active_count() + engine.chunking_count(),
            "max_slots": engine.max_batch,
            "queued_requests": self.scheduler.queue_depth(),
            "queue_depth": self.scheduler.queue_depth(),
            "adoptions_pending": len(self._adoptions),
            "degradation_level": self._degradation_level,
            # Which weights this replica serves (deploy generations key
            # KV portability and placement on it).
            "weights_fp": getattr(engine, "weights_fp", None),
            "kv_pages_free": (
                engine.pool.free_count() if engine.paged else None
            ),
            "kv_pages_total": (
                engine.kv_pages - 1 if engine.paged else None
            ),
            # Adapter-aware router affinity reads this: same-adapter
            # traffic lands where the adapter is already resident.
            "adapters_resident": (
                engine.adapters.resident()
                if engine.adapters is not None else None
            ),
        }

    def close(self) -> None:
        self._stopping = True
        self._wake.set()
        self._thread.join(timeout=10.0)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- engine loop + watchdog ------------------------------------------

    def _fail_all(self, msg: str, release_slots: bool) -> None:
        """Fail every in-flight and queued request with a structured
        error.  ``release_slots=False`` is the watchdog path: the loop
        thread may still be wedged inside the engine, so only the
        request STREAMS are failed (unblocking clients) — engine/slot
        state is cleaned up by the loop thread if it ever returns."""
        engine, sched = self.engine, self.scheduler
        admitting = self._admitting_req
        if admitting is not None and admitting.state == "active":
            admitting.finish("error", msg)
            if release_slots:
                self._admitting_req = None
                if admitting.slot >= 0:
                    engine._release_slot_pages(admitting.slot, donate=False)
                    try:
                        sched.release(admitting.slot)
                    except ValueError:
                        pass
        for slot, req in list(engine._active.items()):
            if req.state == "active":
                req.finish("error", msg)
            if release_slots:
                engine._active.pop(slot, None)
                engine._release_slot_pages(slot, donate=False)
                try:
                    sched.release(slot)
                except ValueError:
                    pass
        if release_slots:
            for slot in engine.abort_chunked(msg):
                try:
                    sched.release(slot)
                except ValueError:
                    pass
        else:
            # Watchdog path: fail the chunk-in-progress STREAMS only —
            # the loop thread may be wedged mid-window.
            for st in list(engine._chunked.values()):
                if st["req"].state == "active":
                    st["req"].finish("error", msg)
        while self._adoptions:
            try:
                req, _, resolver = self._adoptions.popleft()
            except IndexError:
                break
            if req.state == "active" or req.state == "queued":
                req.finish("error", msg)
            if resolver is not None:
                # The remote router retries its other candidates with
                # its own payload copy — "unhealthy" is its retryable
                # fall-through signal.
                resolver("unhealthy", msg)
        for req in sched.drain_pending():
            req.finish("error", msg)
        for req in engine.drain_preempted():
            req.finish("error", msg)

    def _mark_unhealthy(self, reason: str) -> None:
        """Declare the engine dead/wedged: stop admission, fail every
        waiting client with a structured error (never hang), surface the
        reason through ``health()``/``/healthz``, and dump the flight
        recorder — its newest ``decode_step`` record names the engine
        step that wedged.  Idempotent."""
        with self._health_lock:
            if not self.healthy:
                return
            self.healthy = False
            self._unhealthy_reason = reason
        self._log.error("serving_unhealthy", reason=reason)
        from ml_trainer_tpu.telemetry.flight import get_recorder

        get_recorder().dump(
            f"serving_unhealthy: {reason}",
            engine_step=self.engine._step_seq,
            active_requests=self.engine.active_count(),
            queued_requests=self.scheduler.queue_depth(),
            # The dump NAMES the requests the wedge/death hurt; their
            # full lifecycle timelines ride in the serving_requests
            # context provider (SloTracker.context_payload).
            active_request_ids=[
                req.id for req in self.engine._active.values()
            ],
        )
        self._fail_all(f"serving engine unhealthy: {reason}",
                       release_slots=False)
        self._wake.set()

    def _watchdog(self) -> None:
        """Detect a wedged engine: work is pending but the loop thread
        has not heartbeaten within ``watchdog_timeout`` (it is stuck in a
        decode/prefill dispatch).  The watchdog cannot un-wedge the
        device program — it fails the CLIENTS fast and poisons the
        server so callers route around it."""
        poll = max(min(self._watchdog_timeout / 5.0, 1.0), 0.01)
        while not self._stopping and self.healthy:
            time.sleep(poll)
            busy = (
                self.engine.active_count() > 0
                or self.engine.chunking_count() > 0
                or self.scheduler.queue_depth() > 0
                or self._admitting_req is not None
                or len(self._adoptions) > 0
            )
            stale = time.monotonic() - self._last_beat
            if busy and stale > self._watchdog_timeout:
                self.metrics.record_watchdog_trip()
                self._mark_unhealthy(
                    f"decode engine wedged: no heartbeat for {stale:.1f}s "
                    f"with {self.engine.active_count()} active and "
                    f"{self.scheduler.queue_depth()} queued request(s)"
                )
                return

    def _loop(self) -> None:
        try:
            self._loop_inner()
        except BaseException as e:  # noqa: BLE001 — thread death is the event
            # The loop thread is dying on something even the per-iteration
            # handler does not catch: propagate to every waiting client
            # instead of leaving their streams blocked forever.
            self._mark_unhealthy(
                f"engine thread died: {type(e).__name__}: {e}"
            )
        finally:
            # Shutdown (or death): fail whatever is still in flight or
            # queued so no caller blocks forever on a stream the engine
            # will never feed.
            msg = (
                "server closed" if self.healthy
                else f"serving engine unhealthy: {self._unhealthy_reason}"
            )
            self._fail_all(msg, release_slots=True)

    def _drain_adoptions(self) -> bool:
        """Import queued KV adoptions into free slots (loop thread only).
        An adoption the pool cannot hold falls back to the ordinary
        requeue path — admission re-prefills from the request's
        committed tokens, the same resume preemption uses."""
        engine, sched = self.engine, self.scheduler
        progressed = False
        for _ in range(len(self._adoptions)):
            try:
                req, export, resolver = self._adoptions.popleft()
            except IndexError:
                break
            if req.expired():
                req.finish(
                    "expired",
                    f"deadline ({req.deadline}s) passed awaiting adoption",
                )
                self.metrics.record_expiry()
                if resolver is not None:
                    self.slo.forget(req)
                    resolver("expired", req.error)
                progressed = True
                continue
            if req.cancel_requested:
                req.finish("error", "cancelled: hedge superseded")
                self.metrics.record_cancellation()
                if resolver is not None:
                    resolver("cancelled", req.error)
                progressed = True
                continue
            slot = sched.acquire_direct(req)
            if slot is None:
                # No free slot right now: park it at the head so the
                # next free slot goes to the oldest adoption.
                self._adoptions.appendleft((req, export, resolver))
                break
            # Tracked like a prefill admission: a crash mid-import is
            # visible to the watchdog/error handler (the request is not
            # in engine._active yet) and fails its stream instead of
            # hanging the client.
            from ml_trainer_tpu.serving.transfer import (
                MigrationCorrupt,
                WeightsMismatch,
            )

            self._admitting_req = req
            try:
                status = engine.import_slot(req, slot, export)
            except MigrationCorrupt as e:
                # The payload failed its CRC gate AT import (the router
                # verifies at deserialization, so this is the last
                # line): refuse the pages, fall back to the ordinary
                # requeue-and-reprefill resume — never adopt garbage,
                # never poison the loop.  With a resolver (fleet RPC)
                # the corrupt verdict is REPORTED instead: the remote
                # router owns the payload and its fallback candidates.
                # A WeightsMismatch is the same refusal shape but its
                # own wire verdict — retrying other candidates of the
                # same generation cannot help, the router must
                # re-prefill instead.
                self._admitting_req = None
                sched.release(slot)
                verdict = (
                    "weights_mismatch"
                    if isinstance(e, WeightsMismatch) else "corrupt"
                )
                req.mark(f"adopt_{verdict}", error=str(e))
                self._log.error(
                    f"serving_adopt_{verdict}", request=req.id,
                    error=str(e),
                )
                if resolver is not None:
                    self.slo.forget(req)
                    resolver(verdict, str(e))
                else:
                    sched.requeue(req)
                progressed = True
                continue
            self._admitting_req = None
            if status == "no_memory":
                sched.release(slot)
                req.mark("adopt_no_memory", kv_pages_free=(
                    engine.pool.free_count() if engine.paged else None
                ))
                if resolver is not None:
                    self.slo.forget(req)
                    resolver("no_memory", "kv pool cannot hold the chain")
                else:
                    sched.requeue(req)
            elif status == "error":
                # The import finished the request with a structured
                # error (e.g. an unregistered adapter on this replica);
                # nothing bound — just hand the slot back.
                sched.release(slot)
                if resolver is not None:
                    resolver("error", req.error)
            else:
                req.mark("adopted", slot=slot)
                if resolver is not None:
                    resolver("adopted", None)
            progressed = True
        return progressed

    def _export_for_migration(self, req: Request, slot: int) -> None:
        """Prefill-and-export hand-off (loop thread only): the request
        just prefilled into ``slot`` and carries a ``migration_sink`` —
        ship its KV to the router instead of decoding here.  The slot's
        pages release with the usual prefix-cache donation, so the
        prompt stays hot on this prefill replica for affinity-routed
        followers."""
        engine, sched = self.engine, self.scheduler
        export = engine.export_slot(slot)
        engine._active.pop(slot, None)
        engine._release_slot_pages(slot, req, donate=True)
        sched.release(slot)
        # The decode replica's tracker takes over at adopt(); before the
        # tracker forgets the request, emit this replica's fragment of
        # the cross-process trace (queue_wait + prefill on THIS lane) so
        # the merged fleet timeline shows where the prefill ran.
        self.slo.observe_export(req)
        self.slo.forget(req)
        req.mark(
            "kv_exported", pages=export.n_pages, kv_bytes=export.nbytes(),
        )
        sink, req.migration_sink = req.migration_sink, None
        try:
            sink(req, export)
        except Exception as e:  # noqa: BLE001 — the sink is router code
            req.finish(
                "error",
                f"kv migration sink failed: {type(e).__name__}: {e}",
            )

    def _fault_hooks(self) -> None:
        """Serving chaos injection (resilience/faults.py): a matching
        ``replica_slow`` fault latches a slow-down window — every loop
        iteration inside it sleeps, the in-process analog of a replica
        whose chips are being throttled.  The busy-iteration counter is
        the trigger clock, so the fault fires while the replica is
        actually serving, not while it idles."""
        from ml_trainer_tpu.resilience.faults import active_plan

        plan = active_plan()
        if plan is None:
            return
        busy = (
            self.engine.active_count() > 0
            or self.engine.chunking_count() > 0
            or self.scheduler.queue_depth() > 0
            or len(self._adoptions) > 0
        )
        if busy:
            self._busy_iters += 1
            fault = plan.fire(
                "replica_slow", step=self._busy_iters,
                host=self.replica_index,
            )
            if fault is not None:
                self._slow_until = time.monotonic() + fault.secs
        self._maybe_slow()

    def _maybe_slow(self) -> None:
        """Inside a ``replica_slow`` window every dispatch (admission,
        decode step, loop pass) pays ~0.5s — a brutally throttled
        replica whose queue genuinely GROWS under load, which the
        hedging/breaker/autoscaler machinery must route around, not
        wait politely for."""
        if time.monotonic() < self._slow_until:
            time.sleep(0.5)

    def _run_evacuation(self) -> None:
        """Role-reassignment drain (loop thread only): export every
        active slot through the migration machinery to the installed
        sink, hand pending adoptions along with their exports, and fail
        queued requests with a retryable ``draining`` error the router
        redistributes.  The replica is empty (and still healthy) when
        this returns."""
        sink, self._evacuate_sink = self._evacuate_sink, None
        engine, sched = self.engine, self.scheduler
        for slot in sorted(engine._active):
            req = engine._active[slot]
            export = engine.export_slot(slot)
            engine._active.pop(slot, None)
            engine._release_slot_pages(slot, req, donate=True)
            sched.release(slot)
            # The adopting replica's tracker takes over (Server.adopt).
            self.slo.forget(req)
            req.mark("evacuated", slot=slot, pages=export.n_pages)
            try:
                sink(req, export)
            except Exception as e:  # noqa: BLE001 — the sink is router code
                req.finish(
                    "error",
                    f"replica draining for role reassignment; evacuation "
                    f"sink failed: {type(e).__name__}: {e}",
                )
        # Chunk-in-progress prompts have no committed tokens yet: fail
        # them with the retryable ``draining`` error (the router
        # resubmits from scratch) instead of exporting half-written
        # pages.
        for st in engine._chunked.values():
            self.slo.forget(st["req"])
        for slot in engine.abort_chunked(
            "replica draining for role reassignment: request "
            "redistributed"
        ):
            try:
                sched.release(slot)
            except ValueError:
                pass
        while self._adoptions:
            try:
                req, export, resolver = self._adoptions.popleft()
            except IndexError:
                break
            self.slo.forget(req)
            if resolver is not None:
                # A fleet-RPC adoption still pending at evacuation: the
                # remote router holds the payload — report "draining"
                # and let it fall to its next candidate.
                resolver(
                    "draining",
                    "replica draining for role reassignment",
                )
                continue
            try:
                sink(req, export)
            except Exception as e:  # noqa: BLE001
                req.finish(
                    "error",
                    f"replica draining for role reassignment; evacuation "
                    f"sink failed: {type(e).__name__}: {e}",
                )
        for req in sched.drain_pending():
            self.slo.forget(req)
            req.finish(
                "error",
                "replica draining for role reassignment: request "
                "redistributed",
            )
        self._evacuated.set()

    def _loop_inner(self) -> None:
        engine, sched = self.engine, self.scheduler
        while not self._stopping and self.healthy:
            self._last_beat = time.monotonic()
            try:
                self._fault_hooks()
                if self._evacuate_sink is not None:
                    self._run_evacuation()
                # Adoptions first: they already spent a prefill on
                # another replica — making them wait behind fresh
                # admissions would waste that work under load.
                progressed = self._drain_adoptions()
                while engine.free_capacity() > 0:
                    got = sched.acquire()
                    if got is None:
                        break
                    req, slot = got
                    self._maybe_slow()
                    # Tracked so a wedge or crash DURING prefill (request
                    # popped from the queue, not yet in engine._active)
                    # is still visible to the watchdog/error handler and
                    # failed with the rest instead of hanging its stream.
                    self._admitting_req = req
                    status = engine.admit(req, slot)
                    self._admitting_req = None
                    progressed = True
                    if status == "no_memory":
                        # Page pressure: hand the slot back, re-queue the
                        # request at the head of its tenant queue, and let
                        # the running requests free pages first.
                        sched.release(slot)
                        sched.requeue(req)
                        break
                    if status == "finished":
                        sched.release(slot)
                    elif status == "active" and req.migration_sink is not None:
                        self._export_for_migration(req, slot)
                    # "chunking" holds its slot: advance_chunks below
                    # runs one window per loop iteration.
                # One chunked-prefill window per slot per iteration,
                # AFTER admissions — short requests admit (and decode,
                # below) between a long prompt's windows instead of
                # waiting out its whole prefill.
                for slot, req, status in engine.advance_chunks():
                    progressed = True
                    if status == "finished":
                        sched.release(slot)
                    elif status == "active" and req.migration_sink is not None:
                        self._export_for_migration(req, slot)
                if engine.active_count():
                    self._maybe_slow()
                    for slot in engine.step():
                        sched.release(slot)
                    # Preempt-and-requeue victims resume from their
                    # committed tokens (head of their tenant queue).
                    for req in engine.drain_preempted():
                        sched.requeue(req)
                    progressed = True
                if not progressed:
                    self._wake.wait(timeout=self._idle_poll)
                    self._wake.clear()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                # Fail every in-flight request loudly rather than hang
                # their streams, then keep serving new ones.
                err = f"{type(e).__name__}: {e}"
                self._log.error("serving_engine_error", error=err)
                self.metrics.record_engine_error()
                admitting, self._admitting_req = self._admitting_req, None
                if admitting is not None and admitting.state == "active":
                    # Crashed mid-prefill: not in engine._active yet, so
                    # the sweep below would miss it.
                    admitting.finish("error", err)
                    if admitting.slot >= 0:
                        engine._release_slot_pages(
                            admitting.slot, donate=False
                        )
                        try:
                            sched.release(admitting.slot)
                        except ValueError:
                            pass
                for slot, req in list(engine._active.items()):
                    if req.state == "active":
                        req.finish("error", err)
                    del engine._active[slot]
                    engine._release_slot_pages(slot, donate=False)
                    try:
                        sched.release(slot)
                    except ValueError:
                        pass
                for slot in engine.abort_chunked(err):
                    try:
                        sched.release(slot)
                    except ValueError:
                        pass
                for req in engine.drain_preempted():
                    req.finish("error", err)

    # -- HTTP front end --------------------------------------------------

    def _register_wire(self, wire_id, req: Request) -> None:
        with self._wire_lock:
            self._wire_streams[int(wire_id)] = req

    def _forget_wire(self, wire_id) -> None:
        with self._wire_lock:
            self._wire_streams.pop(int(wire_id), None)

    def serve_http(self, host: str = "127.0.0.1", port: int = 0):
        """Start the stdlib HTTP front end (daemon thread); returns the
        bound ``(host, port)``.  Explicitly opt-in — nothing listens
        unless this is called (or ``http_port`` was passed)."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet: we have metrics
                pass

            def _send(self, code: int, payload: dict,
                      retry_after: Optional[float] = None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                if retry_after is not None:
                    self.send_header(
                        "Retry-After",
                        str(max(1, int(round(retry_after)))),
                    )
                self.end_headers()
                self.wfile.write(body)

            def _send_text(self, code: int, text: str, content_type: str):
                body = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            # -- fleet NDJSON streaming (serving/fleet.py) ------------
            # HTTP/1.0 close-delimited bodies: no Content-Length, the
            # connection closing marks the end of the stream — the
            # stdlib client reads line-by-line until EOF.

            def _ndjson_start(self):
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.end_headers()

            def _ndjson(self, obj) -> bool:
                try:
                    self.wfile.write(json.dumps(obj).encode() + b"\n")
                    self.wfile.flush()
                    return True
                except (ConnectionError, OSError):
                    return False

            def _stream_tokens(self, req):
                """Pump ``req``'s stream to the socket as NDJSON lines
                until terminal: ``{"t": token}`` per token, ``{"m":
                b64(payload)}`` + ``{"done": {"state": "migrated"}}``
                when a migration sink fires, else a final ``{"done":
                {...}}``.  A vanished client cancels server-side."""
                from ml_trainer_tpu.serving import transfer

                while True:
                    try:
                        item = req._stream.get(timeout=600.0)
                    except queue.Empty:
                        server.cancel(req)
                        self._ndjson({"done": {
                            "state": "error",
                            "error": "serving engine unhealthy: stream "
                                     "stalled past 600s",
                        }})
                        return
                    if item == _DONE:
                        done = {"state": req.state}
                        if req.error is not None:
                            done["error"] = req.error
                        if req.retry_after is not None:
                            done["retry_after"] = req.retry_after
                        self._ndjson({"done": done})
                        return
                    if (isinstance(item, tuple) and len(item) == 2
                            and item[0] == _KV_MIGRATE):
                        payload = transfer.to_bytes(item[1])
                        if self._ndjson(
                            {"m": base64.b64encode(payload).decode()}
                        ):
                            self._ndjson({"done": {"state": "migrated"}})
                        return
                    if not self._ndjson({"t": int(item)}):
                        server.cancel(req)
                        return

            def _post_stream(self):
                """POST /v1/stream: the fleet's cross-process
                ``submit_request``.  The FIRST NDJSON line is the
                synchronous admission verdict (``accepted`` or a mapped
                structured refusal), then tokens stream."""
                from ml_trainer_tpu.serving.transfer import (
                    request_from_wire,
                )

                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    req = request_from_wire(body)
                except (KeyError, TypeError, ValueError,
                        json.JSONDecodeError) as e:
                    self._send(400, {"error": f"{type(e).__name__}: {e}"})
                    return
                if req.trace_ctx is None:
                    req.trace_ctx = _trace_ctx_header(self.headers)
                if body.get("migrate"):
                    # Prefill-and-export: the sink pushes the export
                    # into THIS stream, which ships it as an "m" line —
                    # the remote router adopts it elsewhere.
                    req.migration_sink = (
                        lambda r, exp: r._stream.put((_KV_MIGRATE, exp))
                    )
                wire_id = body.get("id", req.id)
                self._ndjson_start()
                try:
                    server.submit_request(req)
                except OverloadShed as e:
                    self._ndjson({"status": "shed", "error": str(e),
                                  "retry_after": e.retry_after})
                    return
                except AdmissionError as e:
                    self._ndjson({"status": "draining", "error": str(e)})
                    return
                except EngineUnhealthy as e:
                    self._ndjson({"status": "unhealthy",
                                  "error": str(e)})
                    return
                except RuntimeError as e:
                    self._ndjson({"status": "closed", "error": str(e)})
                    return
                server._register_wire(wire_id, req)
                try:
                    self._ndjson({"status": "accepted",
                                  "replica": server.name or None})
                    self._stream_tokens(req)
                finally:
                    server._forget_wire(wire_id)

            def _post_adopt(self):
                """POST /v1/adopt: the fleet's cross-process ``adopt``.
                The serialized ``KVSlotExport`` rides as the raw body
                (request identity in the ``X-Request-Meta`` header) and
                is CRC-VERIFIED HERE, at the receiving process; the
                first NDJSON line is the structured import verdict the
                remote router maps back into its fallback-candidate
                loop."""
                from ml_trainer_tpu.serving import transfer

                try:
                    meta = json.loads(
                        self.headers.get("X-Request-Meta", "{}")
                    )
                    n = int(self.headers.get("Content-Length", 0))
                    payload = self.rfile.read(n)
                    req = transfer.request_from_wire(meta)
                except (KeyError, TypeError, ValueError,
                        json.JSONDecodeError) as e:
                    self._send(400, {"error": f"{type(e).__name__}: {e}"})
                    return
                if req.trace_ctx is None:
                    req.trace_ctx = _trace_ctx_header(self.headers)
                self._ndjson_start()
                try:
                    export = transfer.from_bytes(payload, verify=True)
                except transfer.MigrationCorrupt as e:
                    self._ndjson({"status": "corrupt", "error": str(e)})
                    return
                resolved: queue.Queue = queue.Queue()
                try:
                    server.adopt(
                        req, export,
                        resolver=lambda s, d: resolved.put((s, d)),
                    )
                except AdmissionError as e:
                    self._ndjson({"status": "draining", "error": str(e)})
                    return
                except EngineUnhealthy as e:
                    self._ndjson({"status": "unhealthy",
                                  "error": str(e)})
                    return
                except RuntimeError as e:
                    self._ndjson({"status": "closed", "error": str(e)})
                    return
                wire_id = meta.get("id", req.id)
                server._register_wire(wire_id, req)
                try:
                    try:
                        status, detail = resolved.get(timeout=120.0)
                    except queue.Empty:
                        server.cancel(req)
                        self._ndjson({"status": "error",
                                      "error": "adoption timed out"})
                        return
                    if status != "adopted":
                        line = {"status": status}
                        if detail:
                            line["error"] = detail
                        self._ndjson(line)
                        return
                    self._ndjson({"status": "adopted"})
                    self._stream_tokens(req)
                finally:
                    server._forget_wire(wire_id)

            def _post_cancel(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    wire_id = int(body["id"])
                except (KeyError, TypeError, ValueError,
                        json.JSONDecodeError) as e:
                    self._send(400, {"error": f"{type(e).__name__}: {e}"})
                    return
                req = server._wire_streams.get(wire_id)
                if req is not None:
                    server.cancel(req)
                self._send(200, {"ok": req is not None})

            def _post_admin(self) -> bool:
                """Fleet control plane; True when the path matched."""
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                except (TypeError, ValueError, json.JSONDecodeError) as e:
                    self._send(400, {"error": f"{type(e).__name__}: {e}"})
                    return True
                path = self.path
                try:
                    if path == "/admin/role":
                        role = body["role"]
                        if role not in ("prefill", "decode", "both"):
                            raise ValueError(f"bad role {role!r}")
                        server.role = role
                        self._send(200, {"ok": True, "role": role})
                    elif path == "/admin/replica_index":
                        # Accept both key spellings: fleet.py's remote
                        # proxy historically posted "replica_index".
                        server.replica_index = int(
                            body["index"] if "index" in body
                            else body["replica_index"]
                        )
                        self._send(200, {"ok": True})
                    elif path == "/admin/degradation":
                        cfg = body.get("config")
                        server.set_degradation(
                            int(body.get("level", 0)),
                            DegradationConfig(**cfg) if cfg else None,
                        )
                        self._send(200, {"ok": True})
                    elif path == "/admin/shed_queued":
                        shed = server.shed_queued(
                            int(body.get("below_priority", 0)),
                            float(body.get("retry_after", 1.0)),
                            cause=str(body.get("cause", "overload")),
                        )
                        self._send(200, {"shed": shed})
                    elif path == "/admin/fail":
                        server._mark_unhealthy(
                            str(body.get("reason", "failed by admin"))
                        )
                        self._send(200, {"ok": True})
                    elif path == "/admin/faults":
                        # Arm a chaos plan in THIS process after spawn
                        # (resilience/faults.py spec syntax) — how the
                        # watchtower smoke injects replica_slow into a
                        # fleet worker once warmup is done.  An empty
                        # spec uninstalls.
                        from ml_trainer_tpu.resilience import faults

                        spec = str(body.get("spec", ""))
                        if spec:
                            faults.install(faults.FaultPlan.parse(spec))
                        else:
                            faults.uninstall()
                        self._send(200, {"ok": True, "spec": spec})
                    elif path == "/admin/evacuate":
                        # Stream-sink evacuation: each active slot's
                        # export rides its OWN open stream as an "m"
                        # line — the remote router's pumps adopt them.
                        ok = server.evacuate(
                            lambda req, exp: req._stream.put(
                                (_KV_MIGRATE, exp)
                            ),
                            timeout=float(body.get("timeout", 30.0)),
                        )
                        self._send(200, {"ok": ok})
                    elif path == "/admin/shutdown":
                        self._send(200, {"ok": True})
                        if getattr(server, "transport", "") == "http":
                            # A fleet worker process: exit outright
                            # once the response is on the wire.
                            def _die():
                                time.sleep(0.25)
                                os._exit(0)

                            threading.Thread(
                                target=_die, daemon=True
                            ).start()
                        threading.Thread(
                            target=server.close, daemon=True
                        ).start()
                    else:
                        return False
                except (KeyError, TypeError, ValueError) as e:
                    self._send(400, {"error": f"{type(e).__name__}: {e}"})
                return True

            def do_GET(self):
                if self.path == "/healthz":
                    payload = server.health()
                    # 503 while wedged/draining so load balancers stop
                    # routing here; the payload says why.
                    self._send(200 if payload["ok"] else 503, payload)
                elif self.path == "/v1/spec":
                    # Fleet geometry handshake (serving/fleet.py): what
                    # a RemoteServer proxy needs to stand in for the
                    # engine object, plus the process compile counter
                    # (the cross-process zero-recompile pin).
                    from ml_trainer_tpu.telemetry import compile_watch

                    eng = server.engine
                    self._send(200, {
                        "max_len": eng.max_len,
                        "vocab_size": eng.vocab_size,
                        "spec_k": eng.spec_k,
                        "kv_page_size": eng.kv_page_size,
                        "paged": eng.paged,
                        "prefill_chunk": eng.prefill_chunk,
                        "max_batch": eng.max_batch,
                        "max_queue": server.scheduler.max_queue,
                        "role": server.role,
                        "pid": os.getpid(),
                        "weights_fp": getattr(eng, "weights_fp", None),
                        "compiles": (
                            compile_watch.compile_count()
                            if compile_watch.installed() else None
                        ),
                    })
                elif self.path == "/metrics":
                    # Prometheus text exposition of the WHOLE process
                    # registry (trainer gauges included when co-resident),
                    # with the serving snapshot published fresh.
                    from ml_trainer_tpu.telemetry.registry import (
                        default_registry,
                    )

                    registry = default_registry()
                    server.metrics.publish(registry)
                    server.slo.publish(registry)
                    # Watchtower sampling rides the publish cadence: the
                    # scrape that reads the gauges also appends them to
                    # the history rings behind /dash.
                    server.watchtower.sample_registry(registry)
                    self._send_text(
                        200, registry.prometheus_text(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                elif self.path == "/metrics.json":
                    self._send(200, server.metrics.snapshot())
                elif self.path == "/trace":
                    # Fleet observability plane: this process's span
                    # buffer plus its clock identity — the router's
                    # save_fleet_trace() merges these into ONE
                    # clock-aligned Perfetto timeline with one lane per
                    # process.
                    self._send(200, spans.trace_payload(server.name))
                elif self.path == "/flight":
                    # The flight-recorder payload WITHOUT a local write:
                    # incident bundles pull a live worker's forensics
                    # over the wire.
                    self._send(
                        200, get_recorder().payload("fleet_fetch")
                    )
                elif self.path == "/slo":
                    # Structured SLO attainment (policy, per-tenant
                    # attainment + burn rate) — the JSON twin of the
                    # serving_slo_* series on /metrics.
                    self._send(200, server.slo.snapshot())
                elif self.path == "/dash":
                    # Watchtower live dashboard: the process's sampled
                    # series as self-contained HTML stat tiles +
                    # sparklines (stdlib only, no external assets).
                    from ml_trainer_tpu.telemetry.watchtower import (
                        render_dashboard,
                    )

                    self._send_text(
                        200,
                        render_dashboard(
                            server.watchtower,
                            title=server.name or server.role,
                        ),
                        "text/html; charset=utf-8",
                    )
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):
                if self.path == "/admin/profile":
                    try:
                        n = int(self.headers.get("Content-Length", 0))
                        body = json.loads(self.rfile.read(n) or b"{}")
                        armed = server.engine._profiler.request(
                            int(body.get("steps", 10)),
                            body.get("logdir"),
                        )
                        self._send(
                            200 if armed else 409,
                            {"armed": armed,
                             "steps": int(body.get("steps", 10))},
                        )
                    except (TypeError, ValueError,
                            json.JSONDecodeError) as e:
                        self._send(
                            400, {"error": f"{type(e).__name__}: {e}"}
                        )
                    return
                if self.path == "/v1/stream":
                    self._post_stream()
                    return
                if self.path == "/v1/adopt":
                    self._post_adopt()
                    return
                if self.path == "/v1/cancel":
                    self._post_cancel()
                    return
                if self.path.startswith("/admin/") and self._post_admin():
                    return
                if self.path != "/v1/generate":
                    self._send(404, {"error": "not found"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    deadline = body.get("deadline")
                    out = server.complete(
                        np.asarray(body["prompt"], np.int32),
                        int(body.get("max_new_tokens", 16)),
                        temperature=float(body.get("temperature", 0.0)),
                        rng=body.get("seed"),
                        eos_token_id=body.get("eos_token_id"),
                        deadline=deadline,
                        tenant=str(body.get("tenant", "default")),
                        priority=int(body.get("priority", 0)),
                        adapter=body.get("adapter"),
                        trace=_trace_ctx_header(self.headers),
                        # The HTTP wait is capped by the client's own
                        # deadline (plus engine slack): a deadline'd
                        # request gets its 504 near the deadline even
                        # when the engine misbehaves.
                        timeout=(
                            float(deadline) + 30.0
                            if deadline is not None else None
                        ),
                    )
                    self._send(200, {
                        "tokens": [int(t) for t in out],
                        "replica": server.name or None,
                    })
                except OverloadShed as e:
                    payload = {"error": str(e)}
                    if e.retry_after is not None:
                        payload["retry_after"] = e.retry_after
                    self._send(503, payload,
                               retry_after=e.retry_after)
                except AdmissionError as e:
                    self._send(429, {"error": str(e)})
                except EngineUnhealthy as e:
                    self._send(503, {"error": str(e)})
                except (DeadlineExceeded, TimeoutError) as e:
                    self._send(504, {"error": str(e)})
                except (KeyError, TypeError, ValueError,
                        json.JSONDecodeError) as e:
                    self._send(400, {"error": f"{type(e).__name__}: {e}"})
                except RuntimeError as e:
                    # Structured terminal errors (redistribution budget,
                    # engine give-ups) must reach the client as JSON,
                    # never a stdlib 500 HTML page.
                    self._send(503, {"error": str(e)})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="serving-http",
        )
        self._http_thread.start()
        return self._httpd.server_address
