"""Thread-safe serving front end over the slot decode engine.

``Server`` owns the engine, the FIFO scheduler, and one worker thread
driving the admit/step loop.  Callers interact through:

* ``submit(prompt, max_new_tokens, ...) -> TokenStream`` — non-blocking;
  the stream iterates tokens as they decode and ``result()`` blocks for
  the full sequence (prompt + continuation, ``generate()``'s layout);
* ``complete(...)`` — the blocking convenience wrapper;
* ``serve_http(port=...)`` — an OPTIONAL stdlib HTTP front end
  (``http.server``; no dependencies), started only when asked for
  (constructor flag ``http_port`` or an explicit call): POST
  ``/v1/generate`` with ``{"prompt": [ids...], "max_new_tokens": n,
  "temperature": t?, "seed": s?, "eos_token_id": e?, "deadline": d?}``
  returns ``{"tokens": [...]}``; GET ``/metrics`` returns the serving
  metrics snapshot; GET ``/healthz`` liveness.  Backpressure maps to
  HTTP 429, deadlines to 504.
"""

from __future__ import annotations

import json
import threading
from typing import Optional

import numpy as np

from ml_trainer_tpu.serving.engine import SlotDecodeEngine
from ml_trainer_tpu.serving.metrics import ServingMetrics
from ml_trainer_tpu.serving.scheduler import (
    AdmissionError,
    DeadlineExceeded,
    FifoScheduler,
    Request,
    _DONE,
)
from ml_trainer_tpu.utils.logging import get_logger


class TokenStream:
    """Streaming view of one request: iterate tokens as they arrive, or
    ``result()`` for the whole sequence."""

    def __init__(self, req: Request, prompt: np.ndarray):
        self._req = req
        self._prompt = prompt
        self._drained = False

    @property
    def request(self) -> Request:
        return self._req

    def __iter__(self):
        while True:
            item = self._req._stream.get()
            if item == _DONE:
                self._drained = True
                self._raise_on_failure()
                return
            yield item

    def _raise_on_failure(self):
        if self._req.state == "expired":
            raise DeadlineExceeded(self._req.error or "deadline exceeded")
        if self._req.state == "error":
            raise RuntimeError(self._req.error or "serving engine error")

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until the request finishes; returns
        ``[prompt + new tokens]`` (1-D int32).  Raises
        ``DeadlineExceeded`` / ``RuntimeError`` on failure states."""
        if not self._drained:
            while True:
                item = self._req._stream.get(timeout=timeout)
                if item == _DONE:
                    self._drained = True
                    break
        self._raise_on_failure()
        return np.concatenate(
            [self._prompt, np.asarray(self._req.tokens, np.int32)]
        )

    @property
    def tokens(self) -> list:
        """Tokens decoded so far (no blocking)."""
        return list(self._req.tokens)


class Server:
    """Continuous-batching serving session: engine + scheduler + one
    worker thread.  Use as a context manager in tests/scripts so the
    thread is joined deterministically."""

    def __init__(self, model, variables: dict, max_batch: int = 8,
                 max_queue: int = 64,
                 metrics: Optional[ServingMetrics] = None,
                 idle_poll: float = 0.02,
                 http_port: Optional[int] = None,
                 spec_k: int = 0, drafter="ngram",
                 draft_variables: Optional[dict] = None):
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self.engine = SlotDecodeEngine(
            model, variables, max_batch=max_batch, metrics=self.metrics,
            spec_k=spec_k, drafter=drafter, draft_variables=draft_variables,
        )
        self.scheduler = FifoScheduler(
            max_batch, max_queue=max_queue, metrics=self.metrics
        )
        self._idle_poll = idle_poll
        self._log = get_logger("ml_trainer_tpu.serving")
        self._wake = threading.Event()
        self._stopping = False
        self._httpd = None
        self._http_thread = None
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="serving-engine"
        )
        self._thread.start()
        if http_port is not None:
            self.serve_http(port=http_port)

    # -- client surface --------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               temperature: float = 0.0, rng=None,
               eos_token_id: Optional[int] = None,
               deadline: Optional[float] = None) -> TokenStream:
        """Enqueue one request (thread-safe).  Raises ``AdmissionError``
        when the queue is at its watermark and ``ValueError`` on a
        request the engine could never serve."""
        if self._stopping:
            raise RuntimeError("server is closed")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("prompt must be a non-empty 1-D token array")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if prompt.size + max_new_tokens + self.engine.spec_k > \
                self.engine.max_len:
            extra = (
                f" + spec_k ({self.engine.spec_k}) — the speculative "
                "verify window needs spec_k tokens of cache slack"
                if self.engine.spec_k else ""
            )
            raise ValueError(
                f"prompt ({prompt.size}) + new tokens ({max_new_tokens})"
                f"{extra} exceeds the model's max_len "
                f"({self.engine.max_len})"
            )
        if eos_token_id is not None and not (
            0 <= eos_token_id < self.engine.vocab_size
        ):
            raise ValueError(
                f"eos_token_id must be in [0, {self.engine.vocab_size}), "
                f"got {eos_token_id}"
            )
        req = Request(
            prompt=prompt, max_new_tokens=int(max_new_tokens),
            temperature=float(temperature), rng=rng,
            eos_token_id=eos_token_id, deadline=deadline,
        )
        self.scheduler.submit(req)
        self._wake.set()
        return TokenStream(req, prompt)

    def complete(self, prompt, max_new_tokens: int,
                 timeout: Optional[float] = None, **kwargs) -> np.ndarray:
        """Blocking one-shot: submit and wait for the full sequence."""
        return self.submit(prompt, max_new_tokens, **kwargs).result(
            timeout=timeout
        )

    def close(self) -> None:
        self._stopping = True
        self._wake.set()
        self._thread.join(timeout=10.0)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- engine loop -----------------------------------------------------

    def _loop(self) -> None:
        engine, sched = self.engine, self.scheduler
        while not self._stopping:
            try:
                progressed = False
                while engine.free_capacity() > 0:
                    got = sched.acquire()
                    if got is None:
                        break
                    req, slot = got
                    if not engine.admit(req, slot):
                        sched.release(slot)
                    progressed = True
                if engine.active_count():
                    for slot in engine.step():
                        sched.release(slot)
                    progressed = True
                if not progressed:
                    self._wake.wait(timeout=self._idle_poll)
                    self._wake.clear()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                # Fail every in-flight request loudly rather than hang
                # their streams, then keep serving new ones.
                self._log.error(
                    "serving_engine_error", error=f"{type(e).__name__}: {e}"
                )
                for slot, req in list(engine._active.items()):
                    req.finish("error", f"{type(e).__name__}: {e}")
                    del engine._active[slot]
                    sched.release(slot)
        # Shutdown: fail whatever is still in flight or queued so no
        # caller blocks forever on a stream the engine will never feed.
        for slot, req in list(engine._active.items()):
            req.finish("error", "server closed")
            del engine._active[slot]
            sched.release(slot)
        while True:
            got = sched.acquire()
            if got is None:
                break
            req, slot = got
            req.finish("error", "server closed")
            sched.release(slot)

    # -- HTTP front end --------------------------------------------------

    def serve_http(self, host: str = "127.0.0.1", port: int = 0):
        """Start the stdlib HTTP front end (daemon thread); returns the
        bound ``(host, port)``.  Explicitly opt-in — nothing listens
        unless this is called (or ``http_port`` was passed)."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet: we have metrics
                pass

            def _send(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._send(200, {"ok": True})
                elif self.path == "/metrics":
                    self._send(200, server.metrics.snapshot())
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):
                if self.path != "/v1/generate":
                    self._send(404, {"error": "not found"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    out = server.complete(
                        np.asarray(body["prompt"], np.int32),
                        int(body.get("max_new_tokens", 16)),
                        temperature=float(body.get("temperature", 0.0)),
                        rng=body.get("seed"),
                        eos_token_id=body.get("eos_token_id"),
                        deadline=body.get("deadline"),
                    )
                    self._send(200, {"tokens": [int(t) for t in out]})
                except AdmissionError as e:
                    self._send(429, {"error": str(e)})
                except DeadlineExceeded as e:
                    self._send(504, {"error": str(e)})
                except (KeyError, TypeError, ValueError,
                        json.JSONDecodeError) as e:
                    self._send(400, {"error": f"{type(e).__name__}: {e}"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="serving-http",
        )
        self._http_thread.start()
        return self._httpd.server_address
