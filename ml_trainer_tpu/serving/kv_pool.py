"""Paged KV memory: fixed-size pages, per-slot page tables, refcounts.

The contiguous slot cache (PR1) reserves ``max_len`` positions per slot
whether a request uses 12 tokens or 500 — concurrency is capped at
``max_batch × max_len`` memory and nothing can be shared.  This module
is the HOST side of the paged replacement (the device side is the
page-table gather/scatter path in ``models/layers.py``):

* **Pages.**  K/V live in one pool of ``num_pages`` fixed-size pages
  per attention layer (``[N, H, page_size, D]`` on device).  Page 0 is
  the TRASH page: no live slot ever maps to it, so inactive rows and
  out-of-range writes land there harmlessly (the device path clips into
  the table; the all-zero table of a freed slot resolves to trash).
* **Page tables.**  Each slot owns a row of ``page_table``
  ``[max_batch, pages_per_slot]`` mapping logical position ``i`` to
  page ``table[slot, i // page_size]``.  The table is HOST-owned (numpy)
  and uploaded to the device cache only when it changes (``dirty``) —
  page allocation is a host decision, the compiled step just reads the
  table as an ordinary input, so allocation never recompiles anything.
* **Refcounts.**  A page's count is the number of slots referencing it
  plus one if the prefix cache (prefix_cache.py) holds it.  ``release``
  returns a page to the free list only at zero, which is what lets N
  requests attend one shared system-prompt page safely.

Single-threaded by design, like the engine that owns it: the serving
loop is the only caller (thread-safe admission lives in the scheduler).
"""

from __future__ import annotations

import collections
from typing import List, Optional

import numpy as np

# Refcount sentinel pinning the trash page: never allocated, never freed.
_TRASH_PIN = 1 << 30


class KVPagePool:
    """Host-side page allocator + per-slot page tables.

    ``num_pages`` counts the whole pool INCLUDING the trash page, so the
    allocatable capacity is ``num_pages - 1``.
    """

    def __init__(self, num_pages: int, page_size: int, max_len: int,
                 max_batch: int):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if max_len % page_size:
            raise ValueError(
                f"max_len ({max_len}) must be a multiple of page_size "
                f"({page_size})"
            )
        self.pages_per_slot = max_len // page_size
        if num_pages < 2:
            raise ValueError(
                f"num_pages must be >= 2 (page 0 is reserved as trash), "
                f"got {num_pages}"
            )
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_len = int(max_len)
        self.page_table = np.zeros(
            (max_batch, self.pages_per_slot), np.int32
        )
        # Pages referenced per slot, logical order (shared prefix pages
        # first, then the slot's own) — the release list on slot free.
        self.slot_pages: List[List[int]] = [[] for _ in range(max_batch)]
        self.refcount = np.zeros(self.num_pages, np.int64)
        self.refcount[0] = _TRASH_PIN
        self._free: collections.deque = collections.deque(
            range(1, self.num_pages)
        )
        # Host table changed since the last device upload (slot freed /
        # pages appended mid-decode): the engine re-uploads before the
        # next dispatch so a recycled page can never be written through a
        # stale device table.
        self.dirty = True

    # -- capacity --------------------------------------------------------

    def free_count(self) -> int:
        return len(self._free)

    def used_count(self) -> int:
        return (self.num_pages - 1) - len(self._free)

    def pages_for(self, tokens: int) -> int:
        """Pages needed to hold ``tokens`` positions."""
        return -(-int(tokens) // self.page_size)

    # -- refcounted page lifecycle ---------------------------------------

    def allocate(self, n: int) -> Optional[List[int]]:
        """Pop ``n`` fresh pages (refcount 1 each), or None — all or
        nothing, so a half-allocated request never wedges the pool."""
        if n > len(self._free):
            return None
        pages = [self._free.popleft() for _ in range(n)]
        for p in pages:
            self.refcount[p] = 1
        return pages

    def retain(self, pages) -> None:
        for p in pages:
            if p == 0:
                raise ValueError("page 0 is the trash page; never retained")
            if self.refcount[p] <= 0:
                raise ValueError(f"retain of dead page {p}")
            self.refcount[p] += 1

    def release(self, pages) -> int:
        """Drop one reference per page; zero-count pages return to the
        free list.  Returns how many pages were actually freed."""
        freed = 0
        for p in pages:
            if p == 0 or self.refcount[p] >= _TRASH_PIN:
                raise ValueError(f"release of reserved page {p}")
            if self.refcount[p] <= 0:
                raise ValueError(f"double free of page {p}")
            self.refcount[p] -= 1
            if self.refcount[p] == 0:
                self._free.append(p)
                freed += 1
        return freed

    # -- slot binding ----------------------------------------------------

    def bind_slot(self, slot: int, pages: List[int]) -> None:
        """Point ``slot`` at ``pages`` (logical order, already counted —
        fresh from ``allocate`` or pinned via ``retain``).  Entries past
        the chain stay 0 (trash)."""
        if self.slot_pages[slot]:
            raise ValueError(f"slot {slot} already bound")
        if len(pages) > self.pages_per_slot:
            raise ValueError(
                f"{len(pages)} pages exceed pages_per_slot "
                f"({self.pages_per_slot})"
            )
        self.slot_pages[slot] = list(pages)
        self.page_table[slot] = 0
        self.page_table[slot, : len(pages)] = pages
        self.dirty = True

    def extend_slot(self, slot: int, pages: List[int]) -> None:
        """Append freshly allocated pages to a slot's chain (decode grew
        past a page boundary)."""
        have = len(self.slot_pages[slot])
        if have + len(pages) > self.pages_per_slot:
            raise ValueError(
                f"slot {slot}: {have}+{len(pages)} pages exceed "
                f"pages_per_slot ({self.pages_per_slot})"
            )
        self.slot_pages[slot].extend(pages)
        self.page_table[slot, have: have + len(pages)] = pages
        self.dirty = True

    def slot_page_count(self, slot: int) -> int:
        return len(self.slot_pages[slot])

    def reset_slot(self, slot: int) -> int:
        """Unbind ``slot`` (finished / expired / preempted / errored):
        release every page it referenced, zero its table row.  Idempotent
        — a second reset of a free slot is a no-op.  Returns pages
        freed (refcount reached zero)."""
        pages, self.slot_pages[slot] = self.slot_pages[slot], []
        if not pages:
            return 0
        self.page_table[slot] = 0
        self.dirty = True
        return self.release(pages)
