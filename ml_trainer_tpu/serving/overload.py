"""Overload survival: the graceful-degradation ladder, per-replica
circuit breakers, and the rolling latency clocks behind hedged prefills.

A serving fleet that only knows how to be healthy has two failure modes
under pressure, both bad: it either admits everything and lets every
request's latency collapse together (the ``docs/serving_slo_cpu.json``
knee — attainment 1.0 -> 0.33 with nothing pushing back), or it falls
over entirely when a replica wedges.  This module is the middle ground
(the Gemma-on-TPU serving paper's SLO/cost framing, PAPERS.md arXiv
2605.25645; TorchTitan's fault-tolerance-as-a-composable-feature thesis
applied to the serve side):

* **Degradation ladder** (:class:`DegradationLadder`): five rungs of
  progressively cheaper service, engaged when SLO burn is high and no
  capacity can be added, exited on recovery — each transition a flight
  event and the ``serving_degradation_level`` gauge:

  ====  ==================  ==============================================
  rung  name                effect (NEW admissions only — see below)
  ====  ==================  ==============================================
  0     ``normal``          full service
  1     ``clamp_tokens``    ``max_new_tokens`` clamped for fresh requests
  2     ``spec_off``        speculative decode disabled (verify compute
                            freed; greedy streams stay byte-identical)
  3     ``hits_only``       fresh admissions must hit the prefix cache —
                            a miss is shed with a structured 503
  4     ``shed_queued``     lowest-priority tenants' QUEUED requests shed
                            (structured 503 + ``retry_after``), and fresh
                            low-priority submissions rejected the same way
  ====  ==================  ==============================================

  Byte-identity contract: every rung acts at ADMISSION time only.  A
  request already streaming when a rung engages keeps its original
  token budget and its committed tokens; a greedy stream crossing a
  ``spec_off`` transition finishes byte-identical to its un-degraded
  run (speculative greedy == vanilla greedy by construction), and a
  resumed/redistributed request (committed tokens > 0) is never
  clamped or shed — tests/test_overload.py pins all of it.

* **Circuit breakers** (:class:`CircuitBreaker`): K consecutive
  failures against a replica open its breaker — the router stops
  placing work there without waiting for the health poller.  After a
  cooldown the breaker goes half-open and admits ONE probe; a probe
  success closes it, a failure re-opens.  The standard three-state
  machine, one per replica, observable as
  ``router_breaker_state{replica=}`` (0 closed / 1 half-open / 2 open).

* **Rolling quantiles** (:class:`RollingQuantile`): bounded windows of
  recent prefill/TTFT latencies; the router's hedging policy fires a
  duplicate prefill on another replica once a request has waited past
  the rolling p99 (docs/serving.md "Hedged prefills").

* **Shed errors** (:class:`OverloadShed`): the structured refusal —
  carries ``retry_after`` seconds, surfaces as HTTP 503 with a
  ``Retry-After`` header and a JSON body naming the rung that shed the
  request.  A shed client knows it was load, not failure, and when to
  come back.

Host-only module: no jax — overload control is pure host policy.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import List, Optional


class OverloadShed(RuntimeError):
    """The deployment refused this request to protect its SLOs (a
    degradation-ladder rung shed it).  ``retry_after`` is the seconds
    the client should back off before retrying; the HTTP front ends
    map this to 503 + ``Retry-After``."""

    def __init__(self, message: str, retry_after: Optional[float] = None):
        super().__init__(message)
        self.retry_after = retry_after


# Ladder rung names, index == level (gauge value).
RUNGS = ("normal", "clamp_tokens", "spec_off", "hits_only", "shed_queued")
MAX_LEVEL = len(RUNGS) - 1


@dataclasses.dataclass(frozen=True)
class DegradationConfig:
    """Ladder knobs.

    ``clamp_tokens``: the per-request ``max_new_tokens`` ceiling rung 1
    imposes on FRESH admissions.  ``retry_after_s``: the backoff a shed
    client is told.  ``shed_below_priority``: rung 4 sheds queued/fresh
    requests with ``priority`` strictly below this (default 1 — the
    default priority 0 traffic sheds, explicitly prioritized traffic
    survives)."""

    clamp_tokens: int = 16
    retry_after_s: float = 2.0
    shed_below_priority: int = 1

    def __post_init__(self):
        if self.clamp_tokens < 1:
            raise ValueError(
                f"clamp_tokens must be >= 1, got {self.clamp_tokens}"
            )
        if self.retry_after_s <= 0:
            raise ValueError(
                f"retry_after_s must be > 0, got {self.retry_after_s}"
            )


class DegradationLadder:
    """The brownout state machine over a set of ``Server`` replicas.

    ``servers`` is a zero-arg callable returning the current replica
    list (the router's fleet can grow/shrink under the autoscaler) or a
    plain list.  ``set_level`` applies the rung to every server
    (idempotent), records the transition as a flight event + history
    row, and rung 4 entry sheds the fleet's queued low-priority
    backlog.  Thread-safe: the autoscaler loop, tests and admin paths
    may all drive it."""

    def __init__(self, servers, config: Optional[DegradationConfig] = None,
                 name: str = "serving"):
        self.config = config if config is not None else DegradationConfig()
        self._servers = servers if callable(servers) else (lambda: list(servers))
        self.name = name
        self._lock = threading.Lock()
        self._level = 0
        self.history: List[dict] = []
        self.shed_total = 0

    @property
    def level(self) -> int:
        return self._level

    @property
    def rung(self) -> str:
        return RUNGS[self._level]

    def set_level(self, level: int, cause: str = "") -> int:
        """Move to ``level`` (clamped to [0, MAX_LEVEL]); returns the
        new level.  Applies the rung to every current server, fires the
        flight event, and on entering rung 4 sheds the queued
        low-priority backlog across the fleet."""
        level = max(0, min(int(level), MAX_LEVEL))
        with self._lock:
            old = self._level
            if level == old:
                return old
            self._level = level
            row = {
                "t": round(time.monotonic(), 3),
                "from": old, "to": level,
                "from_rung": RUNGS[old], "to_rung": RUNGS[level],
                "cause": cause,
            }
            self.history.append(row)
        from ml_trainer_tpu.telemetry.flight import get_recorder

        get_recorder().record(
            "degradation", ladder=self.name, level=level,
            rung=RUNGS[level], previous=RUNGS[old], cause=cause,
        )
        shed = 0
        for server in self._servers():
            server.set_degradation(level, self.config)
            if level >= 4 and old < 4:
                shed += server.shed_queued(
                    self.config.shed_below_priority,
                    self.config.retry_after_s,
                    cause=cause or "degradation ladder rung 4",
                )
        if shed:
            with self._lock:
                self.shed_total += shed
        return level

    def step_up(self, cause: str = "") -> int:
        return self.set_level(self._level + 1, cause)

    def step_down(self, cause: str = "") -> int:
        return self.set_level(self._level - 1, cause)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "level": self._level,
                "rung": RUNGS[self._level],
                "transitions": len(self.history),
                "shed_total": self.shed_total,
                "history": [dict(r) for r in self.history],
            }

    def publish(self, registry=None) -> None:
        """``serving_degradation_level`` (the dashboard's brownout
        gauge) + transition/shed counters."""
        from ml_trainer_tpu.telemetry.registry import default_registry

        r = registry if registry is not None else default_registry()
        with self._lock:
            level, transitions, shed = (
                self._level, len(self.history), self.shed_total
            )
        r.gauge(
            "serving_degradation_level",
            "active degradation-ladder rung (0 normal .. 4 shed_queued)",
        ).set(float(level))
        r.gauge(
            "serving_degradation_transitions_total",
            "degradation-ladder rung transitions",
        ).set(float(transitions))
        r.gauge(
            "serving_degradation_shed_total",
            "queued/fresh requests shed by the ladder",
        ).set(float(shed))


# ------------------------------------------------------ circuit breaker

CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """Three-state per-replica breaker (thread-safe).

    ``threshold`` consecutive failures open the breaker; after
    ``cooldown_s`` it half-opens and ``allow()`` admits exactly one
    probe; the probe's ``record_success``/``record_failure`` closes or
    re-opens it.  ``clock`` is injectable for tests."""

    def __init__(self, threshold: int = 3, cooldown_s: float = 2.0,
                 clock=time.monotonic):
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        if cooldown_s <= 0:
            raise ValueError(f"cooldown_s must be > 0, got {cooldown_s}")
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_out = False
        self.transitions: List[dict] = []

    def _transition(self, state: str, cause: str) -> None:
        # Caller holds the lock.
        if state == self._state:
            return
        self.transitions.append({
            "t": round(self._clock(), 3),
            "from": self._state, "to": state, "cause": cause,
        })
        self._state = state

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:
        # Caller holds the lock.
        if (
            self._state == OPEN
            and self._clock() - self._opened_at >= self.cooldown_s
        ):
            self._transition(HALF_OPEN, "cooldown elapsed")
            self._probe_out = False

    def allow(self) -> bool:
        """May the caller place a request on this replica right now?
        Closed: yes.  Open: no (until the cooldown half-opens it).
        Half-open: exactly one caller gets True (the probe) until its
        outcome is recorded."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and not self._probe_out:
                self._probe_out = True
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._probe_out = False
            if self._state != CLOSED:
                self._transition(CLOSED, "probe succeeded")

    def record_failure(self, cause: str = "") -> None:
        with self._lock:
            self._consecutive += 1
            self._probe_out = False
            if self._state == HALF_OPEN:
                self._opened_at = self._clock()
                self._transition(OPEN, cause or "probe failed")
            elif (
                self._state == CLOSED
                and self._consecutive >= self.threshold
            ):
                self._opened_at = self._clock()
                self._transition(
                    OPEN,
                    cause or f"{self._consecutive} consecutive failures",
                )

    def gauge_value(self) -> int:
        return _STATE_GAUGE[self.state]


# ----------------------------------------------------- rolling quantile

class RollingQuantile:
    """Bounded window of recent observations with on-demand quantiles —
    the hedging clock (``hedge after the rolling p99``).  Thread-safe;
    ``quantile`` returns ``default`` until ``min_samples`` arrive so a
    cold fleet never hedges off two data points."""

    def __init__(self, window: int = 256, min_samples: int = 8,
                 default: float = 1.0):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self._lock = threading.Lock()
        self._vals: collections.deque = collections.deque(maxlen=window)
        self.min_samples = int(min_samples)
        self.default = float(default)

    def observe(self, value: float) -> None:
        with self._lock:
            self._vals.append(float(value))

    def __len__(self) -> int:
        with self._lock:
            return len(self._vals)

    def quantile(self, q: float) -> float:
        with self._lock:
            vals = sorted(self._vals)
        if len(vals) < self.min_samples:
            return self.default
        i = min(len(vals) - 1, int(q * (len(vals) - 1) + 0.5))
        return vals[i]
