"""Admission control: multi-tenant weighted scheduling + slot bookkeeping.

PR1's scheduler was FIFO with backpressure — correct for one well-behaved
caller, defenseless against the production reality of many tenants with
unequal importance: one chatty tenant starves everyone behind a shared
watermark.  This scheduler keeps the same hand-off surface (``submit`` /
``acquire`` / ``release`` / ``drain_pending``) and replaces arrival-order
admission with:

* **Per-tenant queues + quotas.**  Every request carries a ``tenant``;
  each tenant has a :class:`TenantConfig` — admission ``weight``,
  ``max_active`` (concurrent slots it may hold) and ``max_queued``
  (its own watermark inside the global one).  Unknown tenants get the
  default config, so single-tenant callers see exactly the old FIFO
  behavior (one tenant, arrival order — ``FifoScheduler`` remains as an
  alias).
* **Weighted admission (stride scheduling).**  Each admission charges
  the picked tenant ``1/weight`` of virtual time; ``acquire`` picks the
  eligible tenant with the lowest pass.  A weight-3 tenant gets 3× the
  admissions of a weight-1 tenant under contention, and an idle tenant
  re-enters at the current floor instead of burning saved-up credit in
  a burst.
* **Priorities.**  Within a tenant, higher ``priority`` admits first;
  ties admit in arrival order.
* **Preempt-and-requeue.**  The paged engine preempts long generations
  under page pressure (engine.py); ``requeue`` puts the victim BACK at
  the front of its tenant's queue (it keeps its original arrival seq, so
  it sorts ahead of later arrivals at the same priority) with its
  generated tokens intact — re-admission resumes from them as a
  prefix, and the prefix cache usually makes the resume prefill cheap.

Deadlines are enforced at every hand-off point exactly as before: a
queued request whose deadline passes is expired instead of admitted, and
the engine expires active requests between decode steps.
"""

from __future__ import annotations

import heapq
import itertools
import queue as _queue
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np


class AdmissionError(RuntimeError):
    """The pending queue (global or per-tenant) is at its watermark; the
    request was rejected."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before it finished."""


class EngineUnhealthy(RuntimeError):
    """The serving engine is wedged, dead, or draining: in-flight
    requests were failed with a structured error and new submissions are
    refused until the server is replaced."""


_ids = itertools.count()

# Stream sentinels (queue items are plain ints otherwise).
_DONE = ("done", None)

DEFAULT_TENANT = "default"


@dataclass
class TenantConfig:
    """Per-tenant scheduling policy.

    ``weight``: share of admissions under contention (stride
    scheduling — a weight-2 tenant admits twice as often as weight-1).
    ``max_active``: concurrent slots the tenant may occupy (None =
    engine-wide limit only).  ``max_queued``: the tenant's own pending
    watermark inside the global ``max_queue`` (None = global only).
    """

    weight: float = 1.0
    max_active: Optional[int] = None
    max_queued: Optional[int] = None

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"weight must be > 0, got {self.weight}")
        if self.max_active is not None and self.max_active < 1:
            raise ValueError(
                f"max_active must be >= 1 or None, got {self.max_active}"
            )
        if self.max_queued is not None and self.max_queued < 0:
            raise ValueError(
                f"max_queued must be >= 0 or None, got {self.max_queued}"
            )


@dataclass
class Request:
    """One generation request moving through queue -> slot -> done.

    ``deadline`` is a relative budget in seconds from submission (wall
    budget, checked with ``time.monotonic``).  ``rng`` seeds sampling for
    ``temperature > 0`` — an int seed or a jax PRNG key; the engine folds
    the per-token counter exactly like ``generate()`` does, so a request
    at seed ``s`` reproduces ``generate(..., rng=jax.random.PRNGKey(s))``
    token-for-token.  ``tenant``/``priority`` feed the multi-tenant
    scheduler; ``preemptions`` counts how often the paged engine evicted
    this request under page pressure (each time it re-queued with its
    generated tokens as a resumable prefix)."""

    prompt: np.ndarray
    max_new_tokens: int
    temperature: float = 0.0
    rng: object = None
    eos_token_id: Optional[int] = None
    deadline: Optional[float] = None
    tenant: str = DEFAULT_TENANT
    priority: int = 0
    # Batched LoRA serving (serving/adapter_pool.py): the named adapter
    # this request decodes with (None = the base model — slot 0, the
    # trash adapter, bit-identical to an adapter-free engine).  Rides
    # shadows/migrations so redistribution keeps the same weights.
    adapter: Optional[str] = None

    id: int = field(default_factory=lambda: next(_ids))
    submitted_at: float = field(default_factory=time.monotonic)
    state: str = "queued"  # queued | active | done | expired | error | shed
    slot: int = -1
    step: int = 0          # tokens sampled so far (the fold_in counter)
    tokens: list = field(default_factory=list)
    # Speculative decoding (engine spec mode): per-request draft state —
    # the drafter reads prompt+tokens as its lookup history, and these
    # counters record how speculation worked out for THIS request
    # (accepted drafts / verify steps -> its personal acceptance rate).
    spec_steps: int = 0
    spec_accepted_tokens: int = 0
    # Paged engine bookkeeping: scheduler arrival seq (requeued victims
    # keep theirs, so they resume ahead of later arrivals), preemption
    # count, and how many prompt tokens the prefix cache let us skip.
    seq: Optional[int] = None
    preemptions: int = 0
    prefix_hit_tokens: int = 0
    # Chunked prefill (engine prefill_chunk mode): how many prefill
    # windows this request's prompt was split into (0 = unchunked).
    prefill_chunks: int = 0
    # Admission returned "no_memory" and the serve loop is retrying:
    # retries skip prefix-cache stat/LRU accounting so a blocked request
    # can't inflate hit rates or re-heat its own prefix pages while the
    # engine is trying to evict its way out of the pressure.
    kv_blocked: bool = False
    # Disaggregated serving (serving/router.py): when set, the serving
    # loop exports the slot's KV right after prefill and hands
    # ``(request, export)`` to this callable instead of decoding in
    # place — the router adopts the request into a decode replica.
    # Cleared at export so a later preempt-resume decodes where it is.
    migration_sink: object = None
    # Fleet trace context (docs/observability.md "Fleet plane"): set by
    # the origin process (the router front end) and carried across every
    # RPC hop (/v1/stream body + /v1/adopt wire meta + X-Trace-Context
    # header), so each process's retrospective request spans share one
    # ``trace_id`` and the merged fleet timeline renders a migrated
    # request as a single causally-ordered track.  Keys: ``trace_id``
    # (the ORIGIN request id — shadows/adoptions mint fresh local ids),
    # ``parent`` (the span that emitted this hop), ``origin_pid``.
    trace_ctx: Optional[dict] = None
    # Overload control (serving/overload.py): ``retry_after`` rides a
    # shed request's structured 503 (state == "shed"); the router's
    # hedging path sets ``cancel_requested`` on the losing duplicate so
    # the serving loop drops it (queued or active) without billing its
    # tenant an SLO miss — the canceller clears ``observer`` first.
    retry_after: Optional[float] = None
    cancel_requested: bool = False
    admitted_at: Optional[float] = None
    error: Optional[str] = None
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    # Lifecycle tracing (docs/observability.md "Serving SLO"): monotonic
    # stamp of every pushed token (TPOT = consecutive deltas), the
    # ordered lifecycle event list (``mark()``), the FIRST admission
    # stamp (queue-wait; ``admitted_at`` is overwritten on a
    # preempt-resume), measured prefill compute seconds, and an optional
    # finish observer (the server's SloTracker) called exactly once.
    token_times: list = field(default_factory=list)
    events: list = field(default_factory=list)
    first_admitted_at: Optional[float] = None
    prefill_secs: float = 0.0
    observer: object = None
    _observed: bool = False
    _stream: _queue.Queue = field(default_factory=_queue.Queue)

    @property
    def deadline_at(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return self.submitted_at + self.deadline

    def expired(self, now: Optional[float] = None) -> bool:
        d = self.deadline_at
        return d is not None and (now or time.monotonic()) > d

    # -- engine-side hand-off -------------------------------------------

    def mark(self, event: str, **extra) -> None:
        """Append one lifecycle event to the request's timeline (the
        single-writer engine/scheduler hand-off points call this; the
        list is only read after ``finish`` or copied defensively)."""
        row = {"t": round(time.monotonic(), 6), "event": event}
        if extra:
            row.update(extra)
        self.events.append(row)

    def push_token(self, token: int) -> None:
        now = time.monotonic()
        self.tokens.append(int(token))
        self.token_times.append(now)
        if self.first_token_at is None:
            self.first_token_at = now
        self._stream.put(int(token))

    def finish(self, state: str = "done", error: Optional[str] = None) -> None:
        self.state = state
        self.error = error
        self.finished_at = time.monotonic()
        self.mark("finish", state=state)
        # Observer BEFORE the stream sentinel: when result() returns,
        # the request's timeline is already in the SLO accounting.
        obs = self.observer
        if obs is not None and not self._observed:
            self._observed = True
            try:
                obs(self)
            except Exception:  # accounting must never block completion
                pass
        self._stream.put(_DONE)

    # -- timeline --------------------------------------------------------

    def tpot_deltas(self) -> list:
        """Inter-token latencies in seconds (client-observed TPOT): the
        gaps between consecutive pushed tokens.  Empty for <2 tokens."""
        tt = list(self.token_times)
        return [tt[i] - tt[i - 1] for i in range(1, len(tt))]

    def timeline(self) -> dict:
        """The structured per-request lifecycle record (JSON-safe): what
        the SLO tracker aggregates, the flight recorder attaches to
        dumps, and the trace emitter renders as nested spans.  Safe to
        call on an in-flight request (defensive copies; derived fields
        are None until their anchor events exist)."""
        first_admit = self.first_admitted_at
        ttft = (
            self.first_token_at - self.submitted_at
            if self.first_token_at is not None else None
        )
        queue_wait = (
            first_admit - self.submitted_at
            if first_admit is not None else None
        )
        e2e = (
            self.finished_at - self.submitted_at
            if self.finished_at is not None else None
        )
        deltas = self.tpot_deltas()
        deltas.sort()

        def _pct(q):
            if not deltas:
                return None
            i = min(len(deltas) - 1, int(q * (len(deltas) - 1) + 0.5))
            return round(deltas[i] * 1e3, 3)

        def _ms(v):
            return round(v * 1e3, 3) if v is not None else None

        return {
            "id": self.id,
            "tenant": self.tenant,
            "adapter": self.adapter,
            "priority": self.priority,
            "state": self.state,
            "prompt_tokens": int(np.asarray(self.prompt).size),
            "new_tokens": len(self.tokens),
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "preemptions": self.preemptions,
            "submitted_at": round(self.submitted_at, 6),
            "finished_at": (
                round(self.finished_at, 6)
                if self.finished_at is not None else None
            ),
            "queue_wait_ms": _ms(queue_wait),
            "prefill_ms": _ms(self.prefill_secs) if self.prefill_secs
            else 0.0,
            "ttft_ms": _ms(ttft),
            "e2e_ms": _ms(e2e),
            "tpot_ms": {
                "mean": round(
                    sum(deltas) / len(deltas) * 1e3, 3
                ) if deltas else None,
                "p50": _pct(0.5),
                "p99": _pct(0.99),
                "max": round(deltas[-1] * 1e3, 3) if deltas else None,
            },
            "error": self.error,
            "events": [dict(e) for e in list(self.events)],
        }


class TenantScheduler:
    """Weighted multi-tenant admission + free-slot pool (thread-safe).

    With one tenant and default config this IS the old bounded FIFO:
    global watermark, arrival order, reject-with-error past the
    watermark (the caller sheds load or retries with jitter, and memory
    stays bounded by ``max_queue + max_batch`` requests).
    """

    def __init__(self, max_batch: int, max_queue: int = 64,
                 metrics=None,
                 tenants: Optional[Dict[str, TenantConfig]] = None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_queue < 0:
            raise ValueError(f"max_queue must be >= 0, got {max_queue}")
        self.max_queue = max_queue
        self._lock = threading.Lock()
        self.tenants: Dict[str, TenantConfig] = dict(tenants or {})
        # heap entries (-priority, seq, req) per tenant
        self._queues: Dict[str, list] = {}
        self._passes: Dict[str, float] = {}
        self._active: Dict[str, int] = {}
        self._slot_tenant: Dict[int, str] = {}
        self._total_queued = 0
        self._seq = itertools.count()
        self._free_slots = list(range(max_batch - 1, -1, -1))  # pop() -> 0 first
        self._metrics = metrics

    def _cfg(self, tenant: str) -> TenantConfig:
        cfg = self.tenants.get(tenant)
        if cfg is None:
            cfg = self.tenants[tenant] = TenantConfig()
        return cfg

    # -- producer side ---------------------------------------------------

    def submit(self, req: Request) -> None:
        """Enqueue or raise ``AdmissionError`` past a watermark (global
        first, then the tenant's own)."""
        with self._lock:
            cfg = self._cfg(req.tenant)
            if self._total_queued >= self.max_queue:
                if self._metrics is not None:
                    self._metrics.record_rejection(req.tenant)
                raise AdmissionError(
                    f"pending queue at watermark ({self.max_queue}); "
                    f"request {req.id} rejected"
                )
            q = self._queues.setdefault(req.tenant, [])
            if cfg.max_queued is not None and len(q) >= cfg.max_queued:
                if self._metrics is not None:
                    self._metrics.record_rejection(req.tenant)
                raise AdmissionError(
                    f"tenant '{req.tenant}' queue at its quota "
                    f"({cfg.max_queued}); request {req.id} rejected"
                )
            self._enqueue(req, q)
            req.mark("queued", tenant_depth=len(q))
            if self._metrics is not None:
                self._metrics.record_admission(
                    self._total_queued, req.tenant, len(q)
                )

    def requeue(self, req: Request) -> None:
        """Put a PREEMPTED request back at the head of its tenant's
        queue (original seq, so it sorts ahead of later arrivals at the
        same priority).  Bypasses the watermarks — the request was
        already admitted once and its client is still streaming."""
        req.state = "queued"
        req.slot = -1
        req.mark("requeued", preemptions=req.preemptions)
        with self._lock:
            self._enqueue(req, self._queues.setdefault(req.tenant, []))

    def _enqueue(self, req: Request, q: list) -> None:
        if req.seq is None:
            req.seq = next(self._seq)
        if not q:
            # A tenant re-entering from idle starts at the current pass
            # floor: it competes fairly from NOW instead of spending its
            # idle time as a burst of back-to-back admissions.
            floor = min(
                (self._passes[t] for t, tq in self._queues.items() if tq),
                default=0.0,
            )
            self._passes[req.tenant] = max(
                self._passes.get(req.tenant, 0.0), floor
            )
        heapq.heappush(q, (-req.priority, req.seq, req))
        self._total_queued += 1

    def queue_depth(self) -> int:
        with self._lock:
            return self._total_queued

    def tenant_depths(self) -> Dict[str, int]:
        with self._lock:
            return {t: len(q) for t, q in self._queues.items() if q}

    # -- engine side -----------------------------------------------------

    def _pick_tenant(self) -> Optional[str]:
        best = None
        for t, q in self._queues.items():
            if not q:
                continue
            cfg = self._cfg(t)
            if (
                cfg.max_active is not None
                and self._active.get(t, 0) >= cfg.max_active
            ):
                continue
            key = (self._passes.get(t, 0.0), t)
            if best is None or key < best[0]:
                best = (key, t)
        return best[1] if best is not None else None

    def acquire(self) -> Optional[tuple]:
        """Next admissible (request, slot) pair, or None.

        Picks the lowest-pass eligible tenant (stride scheduling), then
        that tenant's highest-priority oldest request.  Skips (and
        expires) queued requests whose deadline already passed — they
        would only waste a prefill.  Returns None when no slot is free,
        nothing is queued, or every queued tenant is at its
        ``max_active`` quota."""
        with self._lock:
            while self._free_slots and self._total_queued:
                tenant = self._pick_tenant()
                if tenant is None:
                    return None
                q = self._queues[tenant]
                req = heapq.heappop(q)[2]
                self._total_queued -= 1
                if self._metrics is not None:
                    self._metrics.record_queue_depth(
                        self._total_queued, tenant, len(q)
                    )
                if req.expired():
                    req.finish(
                        "expired",
                        f"deadline ({req.deadline}s) passed while queued",
                    )
                    if self._metrics is not None:
                        self._metrics.record_expiry()
                    continue
                if req.cancel_requested:
                    # A hedging loser: the router already stopped
                    # reading this stream and cleared its observer.
                    req.finish("error", "cancelled: hedge superseded")
                    continue
                cfg = self._cfg(tenant)
                self._passes[tenant] = (
                    self._passes.get(tenant, 0.0) + 1.0 / cfg.weight
                )
                self._active[tenant] = self._active.get(tenant, 0) + 1
                slot = self._free_slots.pop()
                self._slot_tenant[slot] = tenant
                req.slot = slot
                req.state = "active"
                req.admitted_at = time.monotonic()
                if req.first_admitted_at is None:
                    req.first_admitted_at = req.admitted_at
                req.mark("admitted", slot=slot)
                return req, slot
            return None

    def acquire_direct(self, req: Request) -> Optional[int]:
        """Claim a free slot for an externally placed request — a
        KV-adopted migration landing from another replica's prefill —
        bypassing the queues.  The request was already admitted (and
        charged) at the router, so tenant ``max_active`` quotas are not
        re-applied here (re-applying them could wedge an adoption whose
        prefill budget is already spent); the tenant's active count IS
        charged so ``release`` bookkeeping stays balanced.  Returns the
        slot, or None when none is free right now."""
        with self._lock:
            if not self._free_slots:
                return None
            self._cfg(req.tenant)
            slot = self._free_slots.pop()
            self._slot_tenant[slot] = req.tenant
            self._active[req.tenant] = self._active.get(req.tenant, 0) + 1
            req.slot = slot
            req.state = "active"
            req.admitted_at = time.monotonic()
            if req.first_admitted_at is None:
                req.first_admitted_at = req.admitted_at
            req.mark("adopt_admitted", slot=slot)
            return slot

    def active_counts(self) -> Dict[str, int]:
        with self._lock:
            return {t: n for t, n in self._active.items() if n}

    def shed_queued(self, below_priority: int,
                    retry_after: Optional[float] = None,
                    cause: str = "overload") -> int:
        """Shed every QUEUED request with ``priority`` strictly below
        ``below_priority`` — the degradation ladder's rung-4 action.
        Each victim finishes in the structured ``shed`` state (the
        client sees a 503 + ``retry_after``, never a hang); requests
        already holding slots are untouched (byte-identity contract:
        running streams finish undegraded).  Returns the shed count."""
        with self._lock:
            victims = []
            for q in self._queues.values():
                keep = [e for e in q if e[2].priority >= below_priority]
                if len(keep) != len(q):
                    victims.extend(
                        e[2] for e in q if e[2].priority < below_priority
                    )
                    q[:] = keep
                    heapq.heapify(q)
            self._total_queued -= len(victims)
        for req in victims:
            req.retry_after = retry_after
            req.finish(
                "shed",
                f"request {req.id} (tenant '{req.tenant}', priority "
                f"{req.priority}) shed from the queue under overload "
                f"({cause}); retry after {retry_after}s",
            )
            if self._metrics is not None:
                self._metrics.record_shed(req.tenant)
        return len(victims)

    def drain_pending(self) -> list:
        """Pop and return EVERY queued request (no slot assignment) — the
        shutdown/watchdog path uses this to fail them loudly instead of
        leaving their streams blocked forever."""
        with self._lock:
            out = [
                entry[2] for q in self._queues.values() for entry in q
            ]
            out.sort(key=lambda r: (-r.priority, r.seq or 0))
            self._queues.clear()
            self._total_queued = 0
            return out

    def release(self, slot: int) -> None:
        """Return a slot to the pool (request finished — EOS, budget,
        deadline, preemption, or error)."""
        with self._lock:
            if slot in self._free_slots:
                raise ValueError(f"slot {slot} is already free")
            tenant = self._slot_tenant.pop(slot, None)
            if tenant is not None:
                self._active[tenant] = max(self._active.get(tenant, 1) - 1, 0)
            self._free_slots.append(slot)

    def free_slot_count(self) -> int:
        with self._lock:
            return len(self._free_slots)


# Back-compat: the single-tenant default config IS the old FIFO scheduler.
FifoScheduler = TenantScheduler
