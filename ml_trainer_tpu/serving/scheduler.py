"""Admission control and slot bookkeeping for the decode engine.

FIFO with backpressure: a bounded pending queue admits requests in
arrival order; past the watermark ``submit`` raises ``AdmissionError``
immediately (reject-with-error beats unbounded queues — the caller can
shed load or retry with jitter, and the engine's memory stays bounded by
``max_queue + max_batch`` requests).  Per-request deadlines are enforced
at every hand-off point: a queued request whose deadline passes is
expired instead of admitted, and the engine expires active requests
between decode steps.  Slots (rows of the engine's preallocated cache
block) recycle the moment a request finishes — EOS, token budget, or
deadline — so the next queued request joins the running batch at a token
boundary.
"""

from __future__ import annotations

import collections
import itertools
import queue as _queue
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np


class AdmissionError(RuntimeError):
    """The pending queue is at its watermark; the request was rejected."""


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before it finished."""


class EngineUnhealthy(RuntimeError):
    """The serving engine is wedged, dead, or draining: in-flight
    requests were failed with a structured error and new submissions are
    refused until the server is replaced."""


_ids = itertools.count()

# Stream sentinels (queue items are plain ints otherwise).
_DONE = ("done", None)


@dataclass
class Request:
    """One generation request moving through queue -> slot -> done.

    ``deadline`` is a relative budget in seconds from submission (wall
    budget, checked with ``time.monotonic``).  ``rng`` seeds sampling for
    ``temperature > 0`` — an int seed or a jax PRNG key; the engine folds
    the per-token counter exactly like ``generate()`` does, so a request
    at seed ``s`` reproduces ``generate(..., rng=jax.random.PRNGKey(s))``
    token-for-token."""

    prompt: np.ndarray
    max_new_tokens: int
    temperature: float = 0.0
    rng: object = None
    eos_token_id: Optional[int] = None
    deadline: Optional[float] = None

    id: int = field(default_factory=lambda: next(_ids))
    submitted_at: float = field(default_factory=time.monotonic)
    state: str = "queued"  # queued | active | done | expired | error
    slot: int = -1
    step: int = 0          # tokens sampled so far (the fold_in counter)
    tokens: list = field(default_factory=list)
    # Speculative decoding (engine spec mode): per-request draft state —
    # the drafter reads prompt+tokens as its lookup history, and these
    # counters record how speculation worked out for THIS request
    # (accepted drafts / verify steps -> its personal acceptance rate).
    spec_steps: int = 0
    spec_accepted_tokens: int = 0
    error: Optional[str] = None
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    _stream: _queue.Queue = field(default_factory=_queue.Queue)

    @property
    def deadline_at(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return self.submitted_at + self.deadline

    def expired(self, now: Optional[float] = None) -> bool:
        d = self.deadline_at
        return d is not None and (now or time.monotonic()) > d

    # -- engine-side hand-off -------------------------------------------

    def push_token(self, token: int) -> None:
        self.tokens.append(int(token))
        if self.first_token_at is None:
            self.first_token_at = time.monotonic()
        self._stream.put(int(token))

    def finish(self, state: str = "done", error: Optional[str] = None) -> None:
        self.state = state
        self.error = error
        self.finished_at = time.monotonic()
        self._stream.put(_DONE)


class FifoScheduler:
    """Bounded FIFO admission + free-slot pool (thread-safe)."""

    def __init__(self, max_batch: int, max_queue: int = 64,
                 metrics=None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_queue < 0:
            raise ValueError(f"max_queue must be >= 0, got {max_queue}")
        self.max_queue = max_queue
        self._lock = threading.Lock()
        self._pending: collections.deque = collections.deque()
        self._free_slots = list(range(max_batch - 1, -1, -1))  # pop() -> 0 first
        self._metrics = metrics

    # -- producer side ---------------------------------------------------

    def submit(self, req: Request) -> None:
        """Enqueue or raise ``AdmissionError`` past the watermark."""
        with self._lock:
            if len(self._pending) >= self.max_queue:
                if self._metrics is not None:
                    self._metrics.record_rejection()
                raise AdmissionError(
                    f"pending queue at watermark ({self.max_queue}); "
                    f"request {req.id} rejected"
                )
            self._pending.append(req)
            if self._metrics is not None:
                self._metrics.record_admission(len(self._pending))

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- engine side -----------------------------------------------------

    def acquire(self) -> Optional[tuple]:
        """Next admissible (request, slot) pair, or None.

        Skips (and expires) queued requests whose deadline already
        passed — they would only waste a prefill.  Returns None when no
        slot is free or the queue is empty."""
        with self._lock:
            while self._pending and self._free_slots:
                req = self._pending.popleft()
                if self._metrics is not None:
                    self._metrics.record_queue_depth(len(self._pending))
                if req.expired():
                    req.finish(
                        "expired",
                        f"deadline ({req.deadline}s) passed while queued",
                    )
                    if self._metrics is not None:
                        self._metrics.record_expiry()
                    continue
                req.slot = self._free_slots.pop()
                req.state = "active"
                return req, req.slot
            return None

    def drain_pending(self) -> list:
        """Pop and return EVERY queued request (no slot assignment) — the
        shutdown/watchdog path uses this to fail them loudly instead of
        leaving their streams blocked forever."""
        with self._lock:
            out = list(self._pending)
            self._pending.clear()
            return out

    def release(self, slot: int) -> None:
        """Return a slot to the pool (request finished — EOS, budget,
        deadline, or error)."""
        with self._lock:
            if slot in self._free_slots:
                raise ValueError(f"slot {slot} is already free")
            self._free_slots.append(slot)

    def free_slot_count(self) -> int:
        with self._lock:
            return len(self._free_slots)
